"""Protocol model checker: conformance, counterexamples, and invariants.

Three layers of pinning (analysis/protocol):

1. **Wire conformance** — the Python frame grammar (wire.py) must be
   byte-identical to core/src/message.cc: golden vectors for every
   FrameType checked against the fixtures in tests/golden/frames/ AND
   against the native encoder (c_api.cc hvd_frame_golden) when the
   library is built.
2. **Counterexample teeth** — the checker must re-derive both PR-14 bugs
   from the pre-fix model flags (the regression traces in
   tests/golden/traces/), and every elastic/tree bug knob must produce
   its named violation.
3. **Spec sweeps** — the fixed models must pass exhaustively: the
   serving composition with >= 10^4 distinct states, the elastic
   succession model, and the item-3 tree spec, plus deterministic
   seeded walks.
"""

import json
import os

import pytest

from horovod_tpu.analysis.protocol import wire
from horovod_tpu.analysis.protocol.checker import (check_bfs, check_walk,
                                                   frames_in_trace,
                                                   replay_trace)
from horovod_tpu.analysis.protocol.machines import (ElasticModel,
                                                    ServingDrainModel,
                                                    TreeModel)
from horovod_tpu.analysis.protocol.replay import env_schedule, format_repro

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FRAMES_DIR = os.path.join(REPO, "tests", "golden", "frames")
TRACES_DIR = os.path.join(REPO, "tests", "golden", "traces")


def _load_trace(fname):
    with open(os.path.join(TRACES_DIR, fname)) as f:
        return json.load(f)


# ---------------------------------------------------------------------------
# Wire conformance
# ---------------------------------------------------------------------------

def test_golden_frames_cover_every_frame_type():
    got = {t for t, _name, _b in wire.golden_frames()}
    assert got == set(wire.FRAME_NAMES), "a FrameType has no golden vector"


@pytest.mark.parametrize("ftype,name,framed",
                         wire.golden_frames(),
                         ids=[n for _t, n, _b in wire.golden_frames()])
def test_golden_fixture_pins_python_mirror(ftype, name, framed):
    path = os.path.join(FRAMES_DIR, f"{ftype:02d}_{name}.bin")
    with open(path, "rb") as f:
        fixture = f.read()
    assert fixture == framed, (
        f"{name}: wire.py no longer reproduces the checked-in golden "
        f"bytes — the Python mirror drifted from the frozen grammar")


@pytest.mark.parametrize("ftype,name,framed",
                         wire.golden_frames(),
                         ids=[n for _t, n, _b in wire.golden_frames()])
def test_golden_fixture_pins_native_encoder(ftype, name, framed):
    from horovod_tpu.core import engine
    native = engine.frame_golden(ftype)
    assert native == framed, (
        f"{name}: c_api.cc hvd_frame_golden disagrees with wire.py — "
        f"message.cc and the Python mirror drifted apart")


def test_frame_roundtrip_through_parse_and_payload_codecs():
    for ftype, name, framed in wire.golden_frames():
        header, payload = wire.parse_frame(framed)
        assert header.type == ftype
        codec = wire.PAYLOAD_CODECS.get(ftype)
        if codec is None:  # HELLO_ACK / HEARTBEAT: empty payloads
            assert payload == b""
            continue
        decoded = codec.decode(payload)
        assert decoded.encode() == payload, f"{name} re-encode drifted"


def test_parse_frame_rejects_corruption():
    _t, _n, framed = wire.golden_frames()[2]  # REQUEST
    flipped = bytearray(framed)
    flipped[-1] ^= 0xFF
    with pytest.raises(wire.WireError, match="CRC"):
        wire.parse_frame(bytes(flipped))
    with pytest.raises(wire.WireError, match="magic"):
        wire.parse_frame(b"\x00" * len(framed))
    with pytest.raises(wire.WireError, match="length mismatch"):
        wire.parse_frame(framed[:-1])


def test_bulk_token_matches_native():
    from horovod_tpu.core import engine
    lib = engine.lib()
    import ctypes
    lib.hvd_bulk_token.restype = ctypes.c_uint64
    lib.hvd_bulk_token.argtypes = [ctypes.c_longlong, ctypes.c_longlong,
                                   ctypes.c_int, ctypes.c_int]
    for args in ((99, 3, 1, 2), (0, 0, 0, 0), (1 << 40, 7, 5, 11)):
        assert wire.bulk_token(*args) == lib.hvd_bulk_token(*args)


# ---------------------------------------------------------------------------
# PR-14 regression traces (tests/golden/traces/)
# ---------------------------------------------------------------------------

def test_trace_lost_completion_fails_on_prefix_model():
    doc = _load_trace("serving_lost_completion.json")
    buggy = ServingDrainModel(**doc["bug_flags"])
    v = replay_trace(buggy, doc["trace"])
    assert getattr(v, "invariant", None) == doc["invariant"], (
        "the reverted model no longer fails this trace — the "
        "counterexample lost its teeth")


def test_trace_lost_completion_passes_on_current_model():
    doc = _load_trace("serving_lost_completion.json")
    final = replay_trace(ServingDrainModel(), doc["trace"])
    assert not hasattr(final, "invariant"), f"fixed model violated: {final}"
    assert all(w.lost == 0 for w in final.workers)


def test_trace_drain_wedge_fails_on_prefix_model():
    doc = _load_trace("serving_drain_wedge.json")
    buggy = ServingDrainModel(**doc["bug_flags"])
    v = replay_trace(buggy, doc["trace"])
    assert getattr(v, "invariant", None) == doc["invariant"]


def test_trace_drain_wedge_passes_on_current_model():
    doc = _load_trace("serving_drain_wedge.json")
    final = replay_trace(ServingDrainModel(), doc["trace"])
    assert not hasattr(final, "invariant"), f"fixed model violated: {final}"


def test_checker_rederives_lost_completion_from_scratch():
    r = check_bfs(ServingDrainModel(deliver_before_tick=False))
    assert r.violation is not None
    assert r.violation.invariant == "no-lost-completion"
    # BFS returns a SHORTEST counterexample; the checked-in trace is one.
    doc = _load_trace("serving_lost_completion.json")
    assert len(r.violation.trace) == len(doc["trace"])


def test_checker_rederives_drain_wedge_from_scratch():
    r = check_bfs(ServingDrainModel(drain_by_protocol=False))
    assert r.violation is not None
    assert r.violation.invariant == "quiescence"
    doc = _load_trace("serving_drain_wedge.json")
    assert len(r.violation.trace) == len(doc["trace"])


def test_replay_rejects_inapplicable_trace():
    with pytest.raises(ValueError, match="not enabled"):
        replay_trace(ServingDrainModel(), [["detect", 0]])


# ---------------------------------------------------------------------------
# Spec sweeps — the fixed models, exhaustively
# ---------------------------------------------------------------------------

def test_serving_fixed_model_exhaustive():
    r = check_bfs(ServingDrainModel())
    assert r.ok, str(r.violation)
    assert r.complete, "frontier not drained: raise max_depth"


def test_serving_fixed_model_at_scale_10k_states():
    # The acceptance bar: the shipped star+elastic+serving-drain
    # composition holds every invariant over >= 10^4 distinct states.
    r = check_bfs(ServingDrainModel(workers=3, reqs=2, crashes=1))
    assert r.ok, str(r.violation)
    assert r.complete
    assert r.states >= 10_000, f"only {r.states} states: model degenerated?"


def test_elastic_fixed_model_exhaustive():
    r = check_bfs(ElasticModel())
    assert r.ok, str(r.violation)
    assert r.complete


def test_tree_spec_model_exhaustive():
    r = check_bfs(TreeModel(), max_depth=60)
    assert r.ok, str(r.violation)
    assert r.complete


@pytest.mark.parametrize("flags,invariant", [
    ({"promotion_bumps_epoch": False}, "single-coordinator"),
    ({"clamp_join_id": False}, "quiescence"),
    ({"idempotent_reissue": False}, "ticket-single-use"),
])
def test_elastic_bug_knobs_produce_named_violations(flags, invariant):
    r = check_bfs(ElasticModel(**flags))
    assert r.violation is not None, f"{flags}: no counterexample found"
    assert r.violation.invariant == invariant, str(r.violation)


@pytest.mark.parametrize("flag", [
    "replicate_before_fanout",
    "root_replicate_before_send",
    "root_replays_stale",
])
def test_tree_ordering_rules_are_load_bearing(flag):
    # The item-3 spec: flip any replication-ordering rule off and some
    # interleaving wedges a member forever.
    r = check_bfs(TreeModel(**{flag: False}), max_depth=60)
    assert r.violation is not None, f"{flag}=False: no counterexample"
    assert r.violation.invariant == "quiescence", str(r.violation)


def test_walk_is_deterministic_for_a_seed():
    a = check_walk(ServingDrainModel(), seed=7, steps=60, walks=20)
    b = check_walk(ServingDrainModel(), seed=7, steps=60, walks=20)
    assert (a.states, a.transitions, a.depth) == \
        (b.states, b.transitions, b.depth)
    c = check_walk(ServingDrainModel(), seed=8, steps=60, walks=20)
    assert (a.states, a.transitions) != (c.states, c.transitions)


# ---------------------------------------------------------------------------
# Model -> wire conformance: traces only speak frames message.cc accepts
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("model,trace", [
    (ServingDrainModel(),
     [["step", 0], ["step", 1], ["deliver_req", 0], ["deliver_req", 1],
      ["quit", 0], ["crash", 1], ["detect", 1], ["deliver_resp", 0]]),
    (ElasticModel(),
     [["progress", ], ["replicate", ], ["deliver_state", ], ["knock", ],
      ["poll_join", ], ["deliver_ack", ], ["fail_coord", "partition"],
      ["promote", ], ["deliver_reconfig", ]]),
    (TreeModel(),
     [["announce", 0, 0], ["announce", 0, 1], ["agg_up", 0],
      ["announce", 1, 0], ["announce", 1, 1], ["agg_up", 1],
      ["root_decide"], ["root_replicate"], ["root_send", 0],
      ["relay_replicate", 0], ["relay_fanout", 0, 0]]),
], ids=["serving", "elastic", "tree"])
def test_model_frames_encode_through_real_grammar(model, trace):
    frames = frames_in_trace(model, trace)
    assert frames, "trace sent nothing: conformance hook is dead"
    seen = set()
    for name, payload_struct, epoch in frames:
        ftype = wire.FRAME_TYPES[name]
        framed = wire.frame(ftype, payload_struct.encode(), epoch)
        header, payload = wire.parse_frame(framed)
        assert header.type == ftype
        assert header.flags == epoch & 0xFFFF
        codec = wire.PAYLOAD_CODECS[ftype]
        assert codec.decode(payload).encode() == payload
        seen.add(name)
    assert len(seen) >= 3, f"trace only exercised {seen}"


# ---------------------------------------------------------------------------
# Counterexample -> fault-schedule translation (replay.py)
# ---------------------------------------------------------------------------

def test_env_schedule_crash_roundtrips_through_faults_parser(monkeypatch):
    from horovod_tpu import faults
    doc = _load_trace("serving_lost_completion.json")
    env = env_schedule(ServingDrainModel(**doc["bug_flags"]), doc["trace"])
    assert env == {"HVD_TPU_FAULT_KILL_RANK": "1",
                   "HVD_TPU_FAULT_KILL_STEP": "0"}
    for k, v in env.items():
        monkeypatch.setenv(k, v)
    plan = faults._plan_from_env()
    assert (plan.kill_rank, plan.kill_step) == (1, 0)


def test_env_schedule_partition_emits_wire_grammar(monkeypatch):
    from horovod_tpu import faults
    model = ElasticModel(promotion_bumps_epoch=False)
    r = check_bfs(model)
    env = env_schedule(model, r.violation.trace)
    assert "HVD_TPU_FAULT_WIRE_PARTITION" in env
    monkeypatch.setenv("HVD_TPU_FAULT_WIRE_PARTITION",
                       env["HVD_TPU_FAULT_WIRE_PARTITION"])
    plan = faults._plan_from_env()
    rank, frame, epoch = plan.wire_partition
    assert rank == 0 and frame >= 0 and epoch >= 0


def test_env_schedule_wedge_needs_no_injector():
    # The negative-id JOIN park wedges with a healthy coordinator: no
    # fault event in the trace, so no injector in the schedule.
    model = ElasticModel(clamp_join_id=False)
    r = check_bfs(model)
    assert env_schedule(model, r.violation.trace) == {}
    repro = format_repro(model, r.violation.trace, r.violation)
    assert "no injector needed" in repro
    assert "quiescence" in repro


def test_format_repro_exports_are_pastable():
    doc = _load_trace("serving_lost_completion.json")
    model = ServingDrainModel(**doc["bug_flags"])
    v = replay_trace(model, doc["trace"])
    repro = format_repro(model, doc["trace"], v)
    assert "export HVD_TPU_FAULT_KILL_RANK=1" in repro
    assert "no-lost-completion" in repro


# ---------------------------------------------------------------------------
# The CI entry point
# ---------------------------------------------------------------------------

def test_modelcheck_cli_green_and_skippable():
    import subprocess
    import sys
    env = {**os.environ, "PYTHONPATH": REPO, "MODELCHECK_DEPTH": "60"}
    run = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.analysis.protocol"],
        capture_output=True, text=True, env=env, timeout=300)
    assert run.returncode == 0, run.stdout + run.stderr
    assert "all invariants hold" in run.stdout
    skipped = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.analysis.protocol"],
        capture_output=True, text=True,
        env={**env, "MODELCHECK_SKIP": "1"}, timeout=60)
    assert skipped.returncode == 0
    assert "skipped" in skipped.stdout
