"""int8 quantized allreduce with per-tensor pmax scales + error feedback.

Beyond the reference's cast-based Compression pair (reference
compression.py:42-63): the wire carries int8 (4x smaller than float32),
correctness comes from per-tensor pmax-agreed scales with a sum-fitting
range, and ``DistributedOptimizer(compression=Compression.int8)`` carries
the quantization residual as error feedback.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

import horovod_tpu as hvd
from horovod_tpu.ops import quantized_grouped_allreduce
from horovod_tpu.training import DistributedEFState, DistributedState


def _chipwise(fn):
    """Run fn per-chip under shard_map with one scalar-batch input row."""
    return hvd.shard(fn, in_specs=hvd.batch_spec(2), out_specs=P())


def test_quantized_allreduce_within_quantization_bound(hvd):
    n = hvd.num_chips()
    rng = np.random.RandomState(1)
    per_chip = rng.randn(n, 33).astype(np.float32)

    @_chipwise
    def reduce_q(x):
        (r,), _ = quantized_grouped_allreduce([x[0]], average=True)
        return r

    got = np.asarray(reduce_q(jnp.asarray(per_chip)))
    want = per_chip.mean(axis=0)
    # Per-element error bound: each chip rounds to its nearest level of
    # size scale = amax/qcap, so |err| <= n*(scale/2)/n = scale/2.
    qcap = max(127 // n, 1)
    scale = np.abs(per_chip).max() / qcap
    np.testing.assert_allclose(got, want, atol=scale / 2 + 1e-7)


def test_quantized_allreduce_exact_on_grid_values(hvd):
    """Values already on the shared quantization grid reduce exactly."""
    n = hvd.num_chips()
    qcap = max(127 // n, 1)
    rng = np.random.RandomState(2)
    levels = rng.randint(-qcap, qcap + 1, size=(n, 16)).astype(np.float32)
    # make amax map exactly: ensure at least one chip holds ±qcap
    levels[0, 0] = qcap

    @_chipwise
    def reduce_q(x):
        (r,), _ = quantized_grouped_allreduce([x[0]], average=False)
        return r

    got = np.asarray(reduce_q(jnp.asarray(levels)))
    np.testing.assert_allclose(got, levels.sum(axis=0), rtol=0, atol=0)


def test_quantized_wire_is_int8(hvd):
    """The all-reduced operand must be int8 in the lowered program — the
    whole point of the feature."""
    n = hvd.num_chips()

    @_chipwise
    def reduce_q(x):
        (r,), _ = quantized_grouped_allreduce([x[0]], average=True)
        return r

    jaxpr = str(jax.make_jaxpr(reduce_q)(jnp.ones((n, 130), jnp.float32)))
    assert "i8[" in jaxpr, jaxpr


def test_quantized_residual_is_the_quantization_error(hvd):
    n = hvd.num_chips()
    rng = np.random.RandomState(3)
    vals = rng.randn(n, 8).astype(np.float32)

    @hvd.shard(in_specs=hvd.batch_spec(2), out_specs=hvd.batch_spec(1))
    def residual(x):
        (r,), (e,) = quantized_grouped_allreduce([x[0]], average=False)
        # local value minus its dequantized representation
        return e[None]

    resid = np.asarray(residual(jnp.asarray(vals)))
    qcap = max(127 // n, 1)
    scale = np.abs(vals).max() / qcap
    assert np.abs(resid).max() <= scale / 2 + 1e-7
    # residual + dequantized(local q) == original value
    q = np.clip(np.round(vals / scale), -qcap, qcap)
    np.testing.assert_allclose(resid, vals - q * scale, atol=1e-6)


def test_int8_error_feedback_training_matches_fp32(hvd):
    """A quadratic problem trained with the int8+EF DistributedOptimizer
    must converge to (nearly) the same parameters as the f32 baseline —
    the error-feedback contract."""
    n = hvd.num_chips()
    rng = np.random.RandomState(4)
    target = rng.randn(6).astype(np.float32)
    x_all = rng.randn(n * 4, 6).astype(np.float32)

    def make_step(opt):
        @jax.jit
        @hvd.shard(in_specs=(P(), P(), hvd.batch_spec(2)),
                   out_specs=(P(), P(), P()))
        def step(w, opt_state, xb):
            def loss_fn(w):
                return jnp.mean((xb @ (w - jnp.asarray(target))) ** 2)

            loss, g = jax.value_and_grad(loss_fn)(w)
            updates, opt_state = opt.update({"w": g}, opt_state, {"w": w})
            return w + updates["w"], opt_state, loss

        return step

    results = {}
    for name, compression in (("f32", hvd.Compression.none),
                              ("int8", hvd.Compression.int8)):
        opt = hvd.DistributedOptimizer(optax.sgd(0.05),
                                       compression=compression)
        w = jnp.zeros(6)
        opt_state = opt.init({"w": w})
        step = make_step(opt)
        for _ in range(200):
            w, opt_state, loss = step(w, opt_state, jnp.asarray(x_all))
        results[name] = (np.asarray(w), float(loss))

    # both converge to the target; int8+EF lands close to the f32 result
    np.testing.assert_allclose(results["f32"][0], target, atol=1e-3)
    np.testing.assert_allclose(results["int8"][0], target, atol=5e-3)


def test_int8_state_carries_error(hvd):
    opt = hvd.DistributedOptimizer(optax.sgd(0.1),
                                   compression=hvd.Compression.int8)
    params = {"w": jnp.ones((4,))}
    state = opt.init(params)
    assert isinstance(state, DistributedEFState)
    np.testing.assert_array_equal(np.asarray(state.error["w"]), np.zeros(4))

    @jax.jit
    @hvd.shard(in_specs=(P(), P()), out_specs=(P(), P()))
    def one(params, state):
        grads = {"w": jnp.asarray([0.33, -0.77, 0.5, 0.0])}
        updates, state = opt.update(grads, state, params)
        return updates, state

    _, state2 = one(params, state)
    assert isinstance(state2, DistributedEFState)
    # residual generally nonzero after a quantized step
    assert np.abs(np.asarray(state2.error["w"])).sum() > 0


def test_int8_compressor_rejects_cast_use(hvd):
    with pytest.raises(NotImplementedError, match="quantized"):
        hvd.Compression.int8.compress(jnp.ones(3))


def test_quantized_eager_process_level(hvd):
    """Eager (no mesh axis bound) routes through the process-level
    (scale, int8) payload path — single process: dequantized round-trip
    within the quantization grid, residual = the local error."""
    vals = jnp.asarray(np.linspace(-1, 1, 9).astype(np.float32))
    (r,), (e,) = quantized_grouped_allreduce([vals], average=False)
    scale = 1.0 / 127.0
    np.testing.assert_allclose(np.asarray(r), np.asarray(vals),
                               atol=scale / 2 + 1e-7)
    np.testing.assert_allclose(np.asarray(r) + np.asarray(e),
                               np.asarray(vals), atol=1e-6)
    with pytest.raises(ValueError, match="floating"):
        quantized_grouped_allreduce([jnp.ones(3, jnp.int32)])


def test_quantized_hierarchical_on_dcn_ici_mesh(hvd):
    """Multi-slice meshes route the int8 sum hierarchically (ICI scatter →
    DCN → ICI gather) — only the int8 shard crosses DCN."""
    import numpy as _np
    from jax.sharding import Mesh

    devs = _np.array(jax.devices()[:8]).reshape(2, 4)
    m = Mesh(devs, ("dcn", "ici"))
    rng = _np.random.RandomState(7)
    vals = rng.randn(8, 256).astype(_np.float32)

    def reduce_q(x):
        (r,), _ = quantized_grouped_allreduce([x[0]], average=True)
        return r

    f = jax.jit(jax.shard_map(reduce_q, mesh=m,
                              in_specs=P(("dcn", "ici")), out_specs=P(),
                              check_vma=False))
    got = _np.asarray(f(jnp.asarray(vals)))
    qcap = 127 // 8
    scale = _np.abs(vals).max() / qcap
    _np.testing.assert_allclose(got, vals.mean(axis=0), atol=scale / 2 + 1e-7)
    jaxpr = str(jax.make_jaxpr(f)(jnp.asarray(vals)))
    assert "i8[" in jaxpr


def test_quantized_all_zero_bucket_stays_finite(hvd):
    """All-zero gradients must reduce to zero, not NaN, in every wire
    dtype (the scale floor guards in the working dtype)."""
    n = hvd.num_chips()
    for dtype in (jnp.float32, jnp.float16, jnp.bfloat16):
        @_chipwise
        def reduce_q(x):
            (r,), (e,) = quantized_grouped_allreduce([x[0]], average=True)
            return r

        got = np.asarray(reduce_q(jnp.zeros((n, 8), dtype)).astype(jnp.float32))
        assert np.isfinite(got).all(), dtype
        np.testing.assert_array_equal(got, np.zeros(8, np.float32))


def test_quantized_rejects_integer_grads(hvd):
    @_chipwise
    def reduce_q(x):
        (r,), _ = quantized_grouped_allreduce([x[0].astype(jnp.int32)])
        return r.astype(jnp.float32)

    with pytest.raises(ValueError, match="floating"):
        reduce_q(jnp.ones((hvd.num_chips(), 4)))


def test_quantized_rejects_width_over_127(hvd, monkeypatch):
    from horovod_tpu.ops import collective_ops

    monkeypatch.setattr(collective_ops, "_data_width", lambda axes: 256)

    @_chipwise
    def reduce_q(x):
        (r,), _ = quantized_grouped_allreduce([x[0]])
        return r

    with pytest.raises(ValueError, match="127"):
        reduce_q(jnp.ones((hvd.num_chips(), 4)))


def test_single_allreduce_int8_routes_to_quantized(hvd):
    n = hvd.num_chips()
    rng = np.random.RandomState(9)
    vals = rng.randn(n, 12).astype(np.float32)

    @_chipwise
    def reduce_one(x):
        return hvd.allreduce(x[0], average=True,
                             compression=hvd.Compression.int8)

    got = np.asarray(reduce_one(jnp.asarray(vals)))
    qcap = max(127 // n, 1)
    scale = np.abs(vals).max() / qcap
    np.testing.assert_allclose(got, vals.mean(axis=0), atol=scale / 2 + 1e-7)


def test_int8_ef_state_checkpoints(hvd, tmp_path):
    """DistributedEFState (inner + error residual) must round-trip through
    the checkpoint layer like any optimizer state — resuming an int8 run
    keeps its error feedback."""
    from horovod_tpu import checkpoint

    opt = hvd.DistributedOptimizer(optax.sgd(0.1),
                                   compression=hvd.Compression.int8)
    params = {"w": jnp.ones((4,))}
    state = opt.init(params)

    @jax.jit
    @hvd.shard(in_specs=(P(), P()), out_specs=(P(), P()))
    def one(params, state):
        grads = {"w": jnp.asarray([0.3, -0.7, 0.5, 0.01])}
        updates, state = opt.update(grads, state, params)
        return updates, state

    _, state = one(params, state)
    checkpoint.save(tmp_path / "ef", state)
    # Restore into a ZEROED template: values must come from disk, not be
    # the template handed back.
    zeros = jax.tree.map(jnp.zeros_like, state)
    restored = checkpoint.restore(tmp_path / "ef", template=zeros)
    assert isinstance(restored, DistributedEFState)
    assert np.abs(np.asarray(state.error["w"])).sum() > 0
    np.testing.assert_allclose(np.asarray(restored.error["w"]),
                               np.asarray(state.error["w"]), atol=1e-7)


def test_checkpoint_migrates_across_compression_modes(hvd, tmp_path):
    """Toggling DistributedOptimizer compression between save and resume
    must migrate the optimizer state (reference keras/__init__.py:115-148
    restore-must-rewrap contract): a plain checkpoint restores into an
    int8-EF optimizer with zero residuals; an EF checkpoint restores into
    a plain optimizer dropping residuals with a warning."""
    from horovod_tpu import checkpoint

    params = {"w": jnp.ones((4,)), "b": jnp.zeros((2,))}
    grads = {"w": jnp.asarray([0.3, -0.7, 0.5, 0.01]),
             "b": jnp.asarray([0.2, -0.1])}
    plain = hvd.DistributedOptimizer(optax.sgd(0.1, momentum=0.9))
    ef = hvd.DistributedOptimizer(optax.sgd(0.1, momentum=0.9),
                                  compression=hvd.Compression.int8)

    # plain save → EF resume: residuals zero-initialized, inner survives.
    _, ps = plain.update(grads, plain.init(params), params)  # momentum != 0
    checkpoint.save(tmp_path / "plain", ps)
    ef_template = jax.tree.map(jnp.zeros_like, ef.init(params))
    with pytest.warns(UserWarning, match="initialized to zero"):
        restored = checkpoint.restore(tmp_path / "plain",
                                      template=ef_template)
    assert isinstance(restored, DistributedEFState)
    for got, want in zip(jax.tree.leaves(restored.inner),
                         jax.tree.leaves(ps.inner)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want))
    for leaf in jax.tree.leaves(restored.error):
        np.testing.assert_array_equal(np.asarray(leaf), 0.0)

    # EF save (non-zero residual) → plain resume: residuals dropped, warned.
    @jax.jit
    @hvd.shard(in_specs=(P(), P()), out_specs=(P(), P()))
    def one(params, state):
        updates, state = ef.update(grads, state, params)
        return updates, state

    _, es = one(params, ef.init(params))
    assert sum(float(np.abs(np.asarray(leaf)).sum())
               for leaf in jax.tree.leaves(es.error)) > 0
    checkpoint.save(tmp_path / "ef2", es)
    plain_template = jax.tree.map(jnp.zeros_like, plain.init(params))
    with pytest.warns(UserWarning, match="dropped"):
        restored2 = checkpoint.restore(tmp_path / "ef2",
                                       template=plain_template)
    assert isinstance(restored2, DistributedState)
    for got, want in zip(jax.tree.leaves(restored2.inner),
                         jax.tree.leaves(es.inner)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want))

    # A genuinely incompatible checkpoint still fails loudly.
    checkpoint.save(tmp_path / "other", {"unrelated": jnp.ones(3)})
    with pytest.raises(Exception):
        checkpoint.restore(tmp_path / "other", template=ef_template)


def test_tiered_int8_on_hierarchical_mesh(hvd):
    """(dcn, ici) mesh: the int8 collective sum-fits PER TIER (ICI
    reduce-scatter at ±(127//ici), requantize, int8 DCN psum) — the route
    that lifts the flat 127-worker cap (reference operations.cc:1025-1177
    hierarchy re-derived for the int8 wire)."""
    import jax as _jax
    from jax.sharding import Mesh

    mesh = Mesh(np.array(_jax.devices()).reshape(2, 4), ("dcn", "ici"))
    vals = np.linspace(-1, 1, 8 * 16).astype(np.float32).reshape(8, 16)

    def f(x):
        (r,), _ = quantized_grouped_allreduce([x[0]], average=False)
        return r

    out = jax.jit(jax.shard_map(f, mesh=mesh, in_specs=P(("dcn", "ici")),
                                out_specs=P(), check_vma=False))(
        jnp.asarray(vals))
    expect = vals.sum(axis=0)
    qcap = 127 // 4
    scale = np.abs(vals).max() / qcap
    # stage-1 rounding (width*scale/2) + stage-2 per-tier requantization
    # (dcn * s1_max/(2*qcap2) grid counts, in value terms times scale).
    bound = 8 * scale / 2 + 2 * (4 * qcap) * scale / (2 * 63) + 1e-6
    assert np.abs(np.asarray(out) - expect).max() <= bound


_WIDTH32_SCRIPT = r"""
import os
# Device-count flag only: the pinned jaxlib aborts on unknown XLA flags
# (the --xla_cpu_collective_call_* timeouts postdate it).
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=32"
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, PartitionSpec as P

import horovod_tpu as hvd

hvd.init()
W, B, D = 32, 4, 16
mesh = Mesh(np.array(jax.devices()).reshape(4, 8), ("dcn", "ici"))
rng = np.random.RandomState(0)
x = rng.randn(W * B, D).astype(np.float32)
w_true = rng.randn(D).astype(np.float32)
y = x @ w_true + 0.01 * rng.randn(W * B).astype(np.float32)
spec = P(("dcn", "ici"))


def run(compression):
    opt = hvd.DistributedOptimizer(optax.sgd(0.05), compression=compression)
    params = {"w": jnp.zeros(D), "b": jnp.zeros(())}
    state = opt.init(params)

    @jax.jit
    def step(params, state, xs, ys):
        def inner(p, s, xb, yb):
            def loss_fn(q):
                pred = xb @ q["w"] + q["b"]
                return jnp.mean((pred - yb) ** 2)
            loss, g = jax.value_and_grad(loss_fn)(p)
            u, s = opt.update(g, s, p)
            return optax.apply_updates(p, u), s, jax.lax.pmean(
                loss, ("dcn", "ici"))
        return jax.shard_map(inner, mesh=mesh,
                             in_specs=(P(), P(), spec, spec),
                             out_specs=(P(), P(), P()),
                             check_vma=False)(params, state, xs, ys)

    losses = []
    for _ in range(25):
        params, state, loss = step(params, state, x, y)
        losses.append(float(loss))
    return losses


base = run(hvd.Compression.none)
q8 = run(hvd.Compression.int8)
print("BASE", base[0], base[-1])
print("Q8", q8[0], q8[-1])
assert q8[-1] < 0.25 * q8[0], f"int8-EF failed to converge: {q8}"
rel = abs(q8[-1] - base[-1]) / max(base[-1], 1e-6)
# Width 32 on the (4, 8) tiered grid: +-15 levels + error feedback tracks
# the fp32 trajectory; a flat 127//32=+-3 grid would not be this close.
assert rel < 0.5, f"int8-EF diverged from fp32: {base[-1]} vs {q8[-1]}"
print("WIDTH32 OK")
"""


def test_int8_ef_convergence_width32(tmp_path):
    """Hierarchical tiered int8 at data width 32 ((dcn=4, ici=8) mesh):
    EF-carried training must track fp32 closely — the VERDICT-r2 concern
    that nobody had measured convergence past width 8."""
    import subprocess
    import sys

    from _timing import scaled

    script = tmp_path / "width32.py"
    script.write_text(_WIDTH32_SCRIPT)
    env = {k: v for k, v in os.environ.items()}
    env["PYTHONPATH"] = REPO
    env.pop("XLA_FLAGS", None)
    out = subprocess.run([sys.executable, str(script)], capture_output=True,
                         text=True, timeout=scaled(420), env=env, cwd=REPO)
    assert out.returncode == 0, (out.stdout[-2000:], out.stderr[-2000:])
    assert "WIDTH32 OK" in out.stdout


def test_quantized_per_tensor_scales_in_mesh(hvd):
    """Compiled path: a tiny tensor grouped with a huge one keeps its own
    quantization grid (per-tensor scales, not per fused bucket)."""
    n = hvd.num_chips()

    @_chipwise
    def reduce_two(x):
        big = jnp.full(4, 10.0) * (x[0, 0] * 0 + 1)   # shard-dependent noop
        tiny = jnp.full(4, 1e-6) * (x[0, 0] * 0 + 1)
        (rb, rt), _ = quantized_grouped_allreduce([big, tiny], average=False)
        return jnp.stack([rb, rt])

    out = np.asarray(reduce_two(jnp.ones((n, 2), jnp.float32)))
    np.testing.assert_allclose(out[0], np.full(4, 10.0 * n), rtol=0.01)
    np.testing.assert_allclose(out[1], np.full(4, 1e-6 * n), rtol=0.01)
    assert np.all(out[1] > 0), "tiny tensor zeroed by a shared bucket scale"


def test_quantized_nonfinite_propagates_in_mesh(hvd):
    """Compiled path: a NaN gradient must dequantize to NaN, not finite."""
    n = hvd.num_chips()

    @_chipwise
    def reduce_nan(x):
        bad = jnp.ones(4) * x[0, 0]   # x carries the NaN in shard 0
        (r,), _ = quantized_grouped_allreduce([bad], average=False)
        return r

    x = np.ones((n, 2), np.float32)
    x[0, 0] = np.nan
    out = np.asarray(reduce_nan(jnp.asarray(x)))
    assert not np.isfinite(out).all(), out


def test_quantized_empty_tensor_in_mesh(hvd):
    """Zero-size leaves (an empty head) must not crash the per-tensor amax."""
    n = hvd.num_chips()

    @_chipwise
    def reduce_with_empty(x):
        full = jnp.ones(4) * x[0, 0]
        empty = jnp.zeros((0,), jnp.float32)
        (rf, re), _ = quantized_grouped_allreduce([full, empty],
                                                  average=False)
        return rf

    out = np.asarray(reduce_with_empty(jnp.ones((n, 2), jnp.float32)))
    np.testing.assert_allclose(out, np.full(4, float(n)), rtol=1e-6)
