"""Peer-replicated checkpoint store (horovod_tpu/replication.py) and the
CheckpointManager peer-restore path (docs/fault_tolerance.md "Async &
peer-replicated checkpointing").

The store tests use a duck-typed engine (the NativeEngine shard API is
three methods plus rank/size/epoch) so the epoch-invalidation semantics
are pinned without a control plane; the manager tests monkeypatch
``peek_engine`` the same way and assert the acceptance bar directly:
peer restore performs ZERO payload reads from disk
(``checkpoint.disk_read_count``), round-trips bit-exact, and an
epoch-stale replica is rejected with a clean disk fallback.  End-to-end
frames over a real control plane are covered by the elastic rejoin test
in tests/test_elastic_reconfig.py and the shard soak in
tests/test_failure_detection.py.
"""

import pickle

import numpy as np
import pytest

from horovod_tpu import checkpoint, replication


class FakeEngine:
    """NativeEngine shard-API duck type: shard_put stamps this engine's
    epoch (exactly what core/src/engine.cc ShardPutSend does) and loops
    the frame into ``inbox`` so drain() on the same object plays the
    RECEIVING rank."""

    def __init__(self, rank=0, size=2, epoch=0):
        self.rank, self.size, self.epoch = rank, size, epoch
        self.sent = []
        self.inbox = []
        self.acks = []

    def shard_put(self, target_rank, step, payload):
        self.sent.append((target_rank, step, bytes(payload)))
        self.inbox.append((self.rank, step, self.epoch, bytes(payload)))
        self.acks.append((self.rank, target_rank, step, self.epoch))
        return True

    def shard_poll(self):
        return self.inbox.pop(0) if self.inbox else None

    def shard_acks(self):
        out, self.acks = self.acks, []
        return out


@pytest.fixture(autouse=True)
def _clean_store():
    replication.clear()
    yield
    replication.clear()


def _entry(owner, step, epoch, state):
    payload = pickle.dumps({"step": step, "state": state, "metadata": {}})
    return replication.ReplicaEntry(owner, step, epoch, payload)


def test_target_rank_is_ring_neighbor():
    assert replication.target_rank(0, 4) == 1
    assert replication.target_rank(3, 4) == 0
    assert replication.target_rank(0, 1) == 0


def test_put_ships_to_neighbor_and_drain_absorbs():
    eng = FakeEngine(rank=1, size=3, epoch=0)
    state = {"w": np.arange(4.0)}
    assert replication.put(7, state, {"rng": [1, 2]}, eng=eng)
    assert eng.sent[0][0] == 2  # ring neighbor of rank 1
    assert replication.drain(eng) == 1
    entry = replication.best(epoch=0)
    assert entry is not None and entry.step == 7 and entry.owner_rank == 1
    doc = replication.decode(entry)
    np.testing.assert_array_equal(doc["state"]["w"], np.arange(4.0))
    assert doc["metadata"] == {"rng": [1, 2]}
    assert replication.stats()["last_acked_step"] == 7


def test_put_refuses_single_rank_jobs():
    assert not replication.put(1, {"w": 0}, eng=FakeEngine(rank=0, size=1))
    assert replication.best(epoch=0) is None


def test_newest_step_per_owner_wins():
    eng = FakeEngine(rank=0, size=2)
    for s in (3, 9, 5):  # out-of-order arrival: 9 must survive
        replication.put(s, {"s": s}, eng=eng)
    replication.drain(eng)
    assert replication.best(epoch=0).step == 9
    assert replication.stats()["replicas"] == 1  # one slot per owner


def test_best_rejects_stale_epoch_and_bump_revalidates():
    eng = FakeEngine(rank=0, size=2, epoch=0)
    replication.put(4, {"s": 4}, eng=eng)
    replication.drain(eng)
    # The membership moved on without this entry being re-stamped: a
    # restore at epoch 1 must NOT see the epoch-0 replica.
    assert replication.best(epoch=1) is None
    assert replication.best(epoch=0) is not None
    # A rank that PARTICIPATED in the reconfig re-stamps its survivors.
    replication.bump_epoch(1)
    assert replication.best(epoch=1).step == 4
    assert replication.best(epoch=0) is None


# ---------------------------------------------------------------------------
# CheckpointManager._restore_from_peers — the acceptance-bar unit tests
# ---------------------------------------------------------------------------

def _np_state(v: float):
    return {"w": np.full(4, v, np.float32), "step_arr": np.array(int(v))}


def _seed_replica(owner, step, epoch, state):
    with replication._lock:
        replication._replicas[owner] = _entry(owner, step, epoch, state)


def test_manager_peer_restore_zero_disk_reads(tmp_path, monkeypatch):
    """A replica at least as new as disk restores with ZERO payload reads
    from disk, bit-exact against what was replicated."""
    from horovod_tpu.core import engine as core_engine

    monkeypatch.setenv("HVD_TPU_CKPT_REPLICATE", "1")
    monkeypatch.setattr(core_engine, "peek_engine",
                        lambda: FakeEngine(rank=1, size=3, epoch=2))
    _seed_replica(owner=2, step=5, epoch=2, state=_np_state(5.0))
    mgr = checkpoint.CheckpointManager(tmp_path / "peer", rank=1, size=1)
    checkpoint.reset_disk_read_count()
    ck = mgr.restore_latest(template=_np_state(0.0), broadcast=False)
    assert ck is not None and ck.step == 5
    np.testing.assert_array_equal(ck.state["w"], np.full(4, 5.0, np.float32))
    assert checkpoint.disk_read_count() == 0


def test_manager_peer_restore_stale_epoch_falls_back_to_disk(tmp_path,
                                                             monkeypatch):
    """An epoch-stale replica (newer step!) must lose to the committed
    disk checkpoint from the current membership."""
    from horovod_tpu.core import engine as core_engine

    monkeypatch.setenv("HVD_TPU_CKPT_REPLICATE", "1")
    monkeypatch.setattr(core_engine, "peek_engine",
                        lambda: FakeEngine(rank=0, size=2, epoch=3))
    mgr = checkpoint.CheckpointManager(tmp_path / "stale", rank=0, size=1)
    mgr.save(2, _np_state(2.0))
    _seed_replica(owner=1, step=9, epoch=1, state=_np_state(9.0))  # stale
    checkpoint.reset_disk_read_count()
    ck = mgr.restore_latest(template=_np_state(0.0), broadcast=False)
    assert ck is not None and ck.step == 2  # disk won
    np.testing.assert_array_equal(ck.state["w"], np.full(4, 2.0, np.float32))
    assert checkpoint.disk_read_count() > 0  # it really came from disk


def test_manager_peer_restore_prefers_newer_disk(tmp_path, monkeypatch):
    """Disk strictly newer than the (epoch-valid) replica wins — a replica
    must never roll training back past a committed checkpoint."""
    from horovod_tpu.core import engine as core_engine

    monkeypatch.setenv("HVD_TPU_CKPT_REPLICATE", "1")
    monkeypatch.setattr(core_engine, "peek_engine",
                        lambda: FakeEngine(rank=0, size=2, epoch=0))
    mgr = checkpoint.CheckpointManager(tmp_path / "newer", rank=0, size=1)
    mgr.save(8, _np_state(8.0))
    _seed_replica(owner=1, step=4, epoch=0, state=_np_state(4.0))
    ck = mgr.restore_latest(template=_np_state(0.0), broadcast=False)
    assert ck is not None and ck.step == 8


def test_manager_peer_restore_disabled_without_knob(tmp_path, monkeypatch):
    from horovod_tpu.core import engine as core_engine

    monkeypatch.delenv("HVD_TPU_CKPT_REPLICATE", raising=False)
    monkeypatch.delenv("HOROVOD_CKPT_REPLICATE", raising=False)
    monkeypatch.setattr(core_engine, "peek_engine",
                        lambda: FakeEngine(rank=0, size=2, epoch=0))
    _seed_replica(owner=1, step=9, epoch=0, state=_np_state(9.0))
    mgr = checkpoint.CheckpointManager(tmp_path / "off", rank=0, size=1)
    assert mgr.restore_latest(template=_np_state(0.0), broadcast=False) \
        is None
