"""ZeRO-sharded peer replica store (horovod_tpu/replication.py) and the
CheckpointManager peer-restore path (docs/fault_tolerance.md "Async &
peer-replicated checkpointing").

The store tests use a duck-typed engine (the NativeEngine shard/ticket API
is a handful of methods plus rank/size/epoch) so the sharding, election,
and epoch-invalidation semantics are pinned without a control plane; the
FakeEngine refuses tickets, so every ship exercises the relay leg of the
fallback chain.  The manager tests monkeypatch ``peek_engine`` the same
way and assert the acceptance bar directly: peer restore performs ZERO
payload reads from disk (``checkpoint.disk_read_count``), round-trips
bit-exact, and an epoch-stale shard set is rejected with a clean disk
fallback.  End-to-end frames over a real control plane and the direct
bulk-stream leg are covered in tests/test_dataplane.py and the elastic
rejoin tests in tests/test_elastic_reconfig.py.
"""

import numpy as np
import pytest

from horovod_tpu import checkpoint, replication


class FakeEngine:
    """NativeEngine shard-API duck type: shard_put stamps this engine's
    epoch (exactly what core/src/engine.cc ShardPutSend does) and loops
    the frame into ``inbox`` so drain() on the same object plays the
    RECEIVING rank.  ticket_request always refuses, so shipping falls
    straight down the chain to the coordinator relay."""

    def __init__(self, rank=0, size=2, epoch=0):
        self.rank, self.size, self.epoch = rank, size, epoch
        self.sent = []
        self.inbox = []
        self.acks = []

    def shard_put(self, target_rank, step, payload):
        self.sent.append((target_rank, step, bytes(payload)))
        self.inbox.append((self.rank, step, self.epoch, bytes(payload)))
        self.acks.append((self.rank, target_rank, step, self.epoch))
        return True

    def shard_poll(self):
        return self.inbox.pop(0) if self.inbox else None

    def shard_acks(self):
        out, self.acks = self.acks, []
        return out

    def ticket_request(self, dst, step, nbytes, manifest=b""):
        return False  # no bulk plane in the duck type: relay leg only

    def ticket_poll(self):
        return None

    def timeline_instant(self, name, args=""):
        pass

    def resize_event(self):
        return None


@pytest.fixture(autouse=True)
def _clean_store():
    replication.clear()
    yield
    replication.clear()


def _np_state(v: float):
    return {"w": np.full(4, v, np.float32), "step_arr": np.array(int(v)),
            "opt": [np.arange(3.0), (1, 2.5)]}


def _seed_full_set(step, epoch, state, n=2, metadata=None):
    """Cut a snapshot into n shards and land ALL of them locally —
    the worldview of a rank whose partners finished replicating."""
    blob = replication.encode_snapshot(step, state, metadata)
    cut, shards = replication.cut_shards(blob, n)
    for i, sh in enumerate(shards):
        assert replication.absorb_remote_shard(
            owner=i % n, step=step, epoch=epoch, shard_index=i,
            cut_size=cut, total_len=len(blob), payload=sh, via="local")
    return blob


def test_target_rank_is_ring_neighbor():
    assert replication.target_rank(0, 4) == 1
    assert replication.target_rank(3, 4) == 0
    assert replication.target_rank(0, 1) == 0


# ---------------------------------------------------------------------------
# snapshot codec + byte-range sharding
# ---------------------------------------------------------------------------

def test_codec_round_trips_nested_trees_bit_exact():
    state = {"a": np.arange(7, dtype=np.int64),
             "nest": {"w": np.full((2, 3), 1.5, np.float32)},
             "seq": [np.array(2.0), (np.arange(2), "tag")],
             "scalar": 3}
    blob = replication.encode_snapshot(11, state, {"rng": [1, 2]})
    doc = replication.decode_snapshot(blob)
    assert doc["step"] == 11 and doc["metadata"] == {"rng": [1, 2]}
    out = doc["state"]
    np.testing.assert_array_equal(out["a"], state["a"])
    np.testing.assert_array_equal(out["nest"]["w"], state["nest"]["w"])
    np.testing.assert_array_equal(out["seq"][1][0], np.arange(2))
    assert out["seq"][1][1] == "tag" and out["scalar"] == 3
    assert isinstance(out["seq"], list) and isinstance(out["seq"][1], tuple)


@pytest.mark.parametrize("n", [1, 2, 3, 4, 7])
def test_cut_shards_partitions_exactly(n):
    blob = bytes(range(256)) * 13  # 3328 bytes, not divisible by most n
    cut, shards = replication.cut_shards(blob, n)
    assert b"".join(shards) == blob
    assert cut == -(-len(blob) // n)
    assert len(shards) == replication.n_shards(len(blob), cut)
    assert all(len(s) == cut for s in shards[:-1])
    assert 0 < len(shards[-1]) <= cut


def test_cut_shards_tiny_blob_never_materializes_empty_shards():
    cut, shards = replication.cut_shards(b"ab", 4)
    assert cut == 1 and shards == [b"a", b"b"]
    assert replication.n_shards(2, cut) == 2


# ---------------------------------------------------------------------------
# store: put/drain/absorb semantics
# ---------------------------------------------------------------------------

def test_put_keeps_own_shard_and_relays_it_to_ring_neighbor():
    eng = FakeEngine(rank=1, size=3, epoch=0)
    assert replication.put(7, _np_state(1.0), {"rng": [1]}, eng=eng)
    assert eng.sent[0][0] == 2  # ring neighbor of rank 1
    assert replication.have_shards(7, 0) == [1]  # kept shard index == rank
    assert replication.drain(eng) == 1  # loopback relay absorbs as well
    s = replication.stats()
    assert s["puts"] == 1 and s["drained"] == 1
    assert s["last_acked_step"] == 7
    rs = replication.replication_stats()
    assert rs["shards_shipped_relay"] == 1
    assert rs["shards_shipped_direct"] == 0


def test_put_refuses_single_rank_jobs():
    assert not replication.put(1, {"w": 0}, eng=FakeEngine(rank=0, size=1))
    assert replication.have_shards(1, 0) == []


def test_absorb_rejects_torn_shards():
    ok = replication.absorb_remote_shard(
        owner=0, step=3, epoch=0, shard_index=0, cut_size=4, total_len=8,
        payload=b"abc", via="relay")  # expect 4 bytes, got 3: torn
    assert not ok
    assert replication.absorb_remote_shard(
        owner=0, step=3, epoch=0, shard_index=2, cut_size=4, total_len=8,
        payload=b"", via="relay") is False  # index beyond the blob
    assert replication.have_shards(3, 0) == []


def test_store_prunes_to_two_newest_steps():
    for step in (3, 9, 5):  # out-of-order arrival
        _seed_full_set(step, 0, _np_state(float(step)))
    steps = sorted({s for (s, _i) in replication._shards})
    assert steps == [5, 9]  # 3 pruned, newest-incomplete insurance kept


def test_drain_ignores_unknown_and_torn_relay_payloads():
    eng = FakeEngine(rank=0, size=2, epoch=0)
    eng.inbox.append((1, 5, 0, b"garbage-from-the-past"))
    wrapped = (replication._WRAP_MAGIC
               + replication._WRAP_HDR.pack(0, 1, 4, 8, 0xDEADBEEF)
               + b"abcd")  # CRC mismatch
    eng.inbox.append((1, 5, 0, wrapped))
    assert replication.drain(eng) == 0
    assert replication.have_shards(5, 0) == []


# ---------------------------------------------------------------------------
# election + epoch invalidation
# ---------------------------------------------------------------------------

def test_elect_needs_complete_set_across_union():
    blob = _seed_full_set(6, 0, _np_state(6.0), n=3)
    cut = -(-len(blob) // 3)
    full = replication.local_inventory(0)
    # Split the inventory across two fake ranks: neither is complete
    # alone, together they cover all three shards.
    a = {6: {"cut": cut, "total": len(blob), "shards": [0, 1]}}
    b = {6: {"cut": cut, "total": len(blob), "shards": [2]}}
    el = replication.elect({0: a, 1: b})
    assert el is not None and el["step"] == 6 and el["n_shards"] == 3
    assert el["holders"][2] == [1]
    # Drop shard 2 everywhere: no complete set, no verdict.
    assert replication.elect({0: a}) is None
    # Sanity: the locally-held set elects too.
    assert replication.elect({-1: full})["step"] == 6


def test_elect_prefers_newest_complete_step_and_skips_malformed():
    inv = {4: {"cut": 2, "total": 4, "shards": [0, 1]},
           9: {"cut": 2, "total": 4, "shards": [0]},  # incomplete
           7: {"cut": 2, "total": 4, "shards": [0, 1]},
           "bad": "not-a-dict"}
    el = replication.elect({0: inv})
    assert el["step"] == 7  # 9 is torn, 7 beats 4


def test_epoch_stale_shards_invisible_until_bump():
    _seed_full_set(4, 0, _np_state(4.0))
    assert replication.restore_local(1) is None  # membership moved on
    assert replication.restore_local(0)["step"] == 4
    replication.bump_epoch(1)  # this rank PARTICIPATED in the reconfig
    assert replication.restore_local(1)["step"] == 4
    assert replication.restore_local(0) is None


def test_restore_local_round_trips_bit_exact():
    state = _np_state(5.0)
    _seed_full_set(5, 2, state, metadata={"rng": [9]})
    doc = replication.restore_local(2)
    assert doc is not None and doc["step"] == 5
    np.testing.assert_array_equal(doc["state"]["w"], state["w"])
    np.testing.assert_array_equal(doc["state"]["opt"][0], state["opt"][0])
    assert doc["metadata"] == {"rng": [9]}


def test_inventory_exchange_pins_own_view():
    eng = FakeEngine(rank=0, size=2, epoch=0)
    _seed_full_set(3, 0, _np_state(3.0))
    inv = replication.send_inventory(eng)
    assert inv[3]["shards"] == [0, 1]
    assert len(eng.sent) == 1  # broadcast to the one peer
    assert replication.inventories(0)[0] == inv  # pinned for election
    assert replication.inventories(1) == {}  # stale-epoch views invisible


def test_reshard_reships_newest_step_to_new_partner():
    eng = FakeEngine(rank=0, size=2, epoch=1)
    _seed_full_set(8, 0, _np_state(8.0))
    replication.bump_epoch(1)
    n = replication.reshard(eng)
    assert n == 2  # both held shards re-shipped (relay leg)
    assert all(dst == 1 for dst, _s, _p in eng.sent)


# ---------------------------------------------------------------------------
# CheckpointManager._restore_from_peers — the acceptance-bar unit tests
# ---------------------------------------------------------------------------

def test_manager_peer_restore_zero_disk_reads(tmp_path, monkeypatch):
    """A complete epoch-valid shard set at least as new as disk restores
    with ZERO payload reads from disk, bit-exact."""
    from horovod_tpu.core import engine as core_engine

    monkeypatch.setenv("HVD_TPU_CKPT_REPLICATE", "1")
    monkeypatch.setattr(core_engine, "peek_engine",
                        lambda: FakeEngine(rank=1, size=3, epoch=2))
    _seed_full_set(5, 2, _np_state(5.0), n=3)
    mgr = checkpoint.CheckpointManager(tmp_path / "peer", rank=1, size=1)
    checkpoint.reset_disk_read_count()
    ck = mgr.restore_latest(template=_np_state(0.0), broadcast=False)
    assert ck is not None and ck.step == 5
    np.testing.assert_array_equal(ck.state["w"], np.full(4, 5.0, np.float32))
    assert checkpoint.disk_read_count() == 0


def test_manager_peer_restore_stale_epoch_falls_back_to_disk(tmp_path,
                                                             monkeypatch):
    """An epoch-stale shard set (newer step!) must lose to the committed
    disk checkpoint from the current membership."""
    from horovod_tpu.core import engine as core_engine

    monkeypatch.setenv("HVD_TPU_CKPT_REPLICATE", "1")
    monkeypatch.setattr(core_engine, "peek_engine",
                        lambda: FakeEngine(rank=0, size=2, epoch=3))
    mgr = checkpoint.CheckpointManager(tmp_path / "stale", rank=0, size=1)
    mgr.save(2, _np_state(2.0))
    _seed_full_set(9, 1, _np_state(9.0))  # stale epoch
    checkpoint.reset_disk_read_count()
    ck = mgr.restore_latest(template=_np_state(0.0), broadcast=False)
    assert ck is not None and ck.step == 2  # disk won
    np.testing.assert_array_equal(ck.state["w"], np.full(4, 2.0, np.float32))
    assert checkpoint.disk_read_count() > 0  # it really came from disk


def test_manager_peer_restore_prefers_newer_disk(tmp_path, monkeypatch):
    """Disk strictly newer than the (epoch-valid) shard set wins — a
    replica must never roll training back past a committed checkpoint."""
    from horovod_tpu.core import engine as core_engine

    monkeypatch.setenv("HVD_TPU_CKPT_REPLICATE", "1")
    monkeypatch.setattr(core_engine, "peek_engine",
                        lambda: FakeEngine(rank=0, size=2, epoch=0))
    mgr = checkpoint.CheckpointManager(tmp_path / "newer", rank=0, size=1)
    mgr.save(8, _np_state(8.0))
    _seed_full_set(4, 0, _np_state(4.0))
    ck = mgr.restore_latest(template=_np_state(0.0), broadcast=False)
    assert ck is not None and ck.step == 8


def test_manager_peer_restore_disabled_without_knob(tmp_path, monkeypatch):
    from horovod_tpu.core import engine as core_engine

    monkeypatch.delenv("HVD_TPU_CKPT_REPLICATE", raising=False)
    monkeypatch.delenv("HOROVOD_CKPT_REPLICATE", raising=False)
    monkeypatch.setattr(core_engine, "peek_engine",
                        lambda: FakeEngine(rank=0, size=2, epoch=0))
    _seed_full_set(9, 0, _np_state(9.0))
    mgr = checkpoint.CheckpointManager(tmp_path / "off", rank=0, size=1)
    assert mgr.restore_latest(template=_np_state(0.0), broadcast=False) \
        is None
