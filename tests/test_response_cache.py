"""Response-cache fast path (docs/response_cache.md).

The eager engine's coordinated response cache must (1) serve stable
schedules without re-negotiation — bit-vector announcements, immediate
cycle wake-up, per-op latency decoupled from HOROVOD_CYCLE_TIME; (2) stay
bit-for-bit compatible with the uncached protocol when
HOROVOD_CACHE_CAPACITY=0; and (3) stay COHERENT: signature changes flush
the entry on every rank in the same tick and renegotiate cleanly, never
diverging ranks or hanging them (the Horovod 0.16 response-cache contract
our 0.15.1 snapshot predates).

Tensors stay tiny and iteration counts low: tier-1 runs under a hard
wall-clock budget.
"""

import multiprocessing
import os
import socket
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

from horovod_tpu.core.engine import (  # noqa: I001
    OP_ALLGATHER,
    OP_ALLREDUCE,
    OP_BROADCAST,
    CollectiveError,
    NativeEngine,
)
from horovod_tpu.core.executors import local_executor

from _timing import scaled
from _tsan import tsan_runtime


def _median(xs):
    xs = sorted(xs)
    return xs[len(xs) // 2]


# ---------------------------------------------------------------------------
# Single-process: stats, fast path, eviction, invalidation
# ---------------------------------------------------------------------------

def test_cache_hits_and_bypassed_ticks():
    eng = NativeEngine(0, 1, executor=local_executor, cycle_time_ms=1.0,
                       cache_capacity=16)
    try:
        for _ in range(4):
            for n in range(3):
                out = eng.synchronize(eng.enqueue(
                    f"c{n}", np.full(4, 2.0, np.float32), OP_ALLREDUCE))
                np.testing.assert_array_equal(out, np.full(4, 2.0, np.float32))
        stats = eng.cache_stats()
    finally:
        eng.shutdown()
    # First sight of each name negotiates (3 misses); every repeat is a hit.
    assert stats["misses"] == 3, stats
    assert stats["hits"] == 9, stats
    assert stats["entries"] == 3 and stats["capacity"] == 16, stats
    # Hit-only cycles skip negotiation metadata entirely.
    assert stats["bypassed_ticks"] > 0, stats


def test_cache_disabled_is_inert():
    """HOROVOD_CACHE_CAPACITY=0 must reproduce the uncached engine: correct
    results, zero counters, no cache machinery on the wire."""
    eng = NativeEngine(0, 1, executor=local_executor, cycle_time_ms=1.0,
                       cache_capacity=0)
    try:
        for _ in range(3):
            out = eng.synchronize(eng.enqueue(
                "off", np.ones(4, np.float32), OP_ALLREDUCE))
            np.testing.assert_array_equal(out, np.ones(4, np.float32))
        stats = eng.cache_stats()
    finally:
        eng.shutdown()
    assert stats == {"hits": 0, "misses": 0, "evictions": 0,
                     "bypassed_ticks": 0, "entries": 0, "capacity": 0}, stats


def test_cache_hit_latency_beats_cycle_time():
    """The event-driven wake-up: with a deliberately huge cycle time, a
    cache-hit enqueue must complete without waiting out the tick, while the
    uncached engine pays the full cycle per op."""
    cycle_ms = 200.0

    def per_op_ms(eng, n_ops):
        samples = []
        for _ in range(n_ops):
            t0 = time.perf_counter()
            eng.synchronize(eng.enqueue("lat", np.ones(64, np.float32),
                                        OP_ALLREDUCE))
            samples.append((time.perf_counter() - t0) * 1000.0)
        return _median(samples)

    warm_eng = NativeEngine(0, 1, executor=local_executor,
                            cycle_time_ms=cycle_ms, cache_capacity=8)
    try:
        warm_eng.synchronize(warm_eng.enqueue(  # populate the entry
            "lat", np.ones(64, np.float32), OP_ALLREDUCE))
        warm = per_op_ms(warm_eng, 5)
        assert warm_eng.cache_stats()["hits"] >= 5
    finally:
        warm_eng.shutdown()

    cold_eng = NativeEngine(0, 1, executor=local_executor,
                            cycle_time_ms=cycle_ms, cache_capacity=0)
    try:
        cold = per_op_ms(cold_eng, 5)
    finally:
        cold_eng.shutdown()

    # Uncached ops wait out the coordination tick; cached ops wake it.
    assert warm < cycle_ms / 2, (warm, cold)
    assert cold > cycle_ms / 2, (warm, cold)
    assert cold > 2 * warm, (warm, cold)


def test_lru_eviction_stays_correct():
    eng = NativeEngine(0, 1, executor=local_executor, cycle_time_ms=1.0,
                       cache_capacity=2)
    try:
        for r in range(3):
            for n in range(4):  # working set (4) > capacity (2): thrash
                out = eng.synchronize(eng.enqueue(
                    f"ev{n}", np.full(2, float(n), np.float32), OP_ALLREDUCE))
                np.testing.assert_array_equal(
                    out, np.full(2, float(n), np.float32))
        stats = eng.cache_stats()
    finally:
        eng.shutdown()
    assert stats["evictions"] > 0, stats
    assert stats["entries"] <= 2, stats


def test_signature_change_invalidates_and_repopulates():
    """Same name, new shape: the stale entry is flushed, the collective
    renegotiates cleanly, and the NEW signature becomes cacheable."""
    eng = NativeEngine(0, 1, executor=local_executor, cycle_time_ms=1.0,
                       cache_capacity=8)
    try:
        for _ in range(2):
            eng.synchronize(eng.enqueue("sig", np.ones(2, np.float32),
                                        OP_ALLREDUCE))
        s1 = eng.cache_stats()
        for _ in range(2):
            out = eng.synchronize(eng.enqueue("sig", np.ones(5, np.float32),
                                              OP_ALLREDUCE))
            assert out.shape == (5,)
        s2 = eng.cache_stats()
    finally:
        eng.shutdown()
    assert s1["hits"] == 1 and s1["misses"] == 1, s1
    # Shape change: one more miss (the stale announcement), then hits resume
    # on the new signature.
    assert s2["misses"] == 2 and s2["hits"] == 2, s2
    assert s2["entries"] == 1, s2


def test_cached_ops_cover_all_types():
    """Allgather/broadcast verdicts cache too (per-rank signatures cover the
    ragged dim 0, so the stored per-rank sizes stay valid on a hit)."""
    eng = NativeEngine(0, 1, executor=local_executor, cycle_time_ms=1.0,
                       cache_capacity=8)
    try:
        x = np.arange(6, dtype=np.int64).reshape(2, 3)
        for _ in range(3):
            np.testing.assert_array_equal(
                eng.synchronize(eng.enqueue("t.ag", x, OP_ALLGATHER)), x)
            np.testing.assert_array_equal(
                eng.synchronize(eng.enqueue("t.bc", x, OP_BROADCAST,
                                            root_rank=0)), x)
        stats = eng.cache_stats()
    finally:
        eng.shutdown()
    assert stats["misses"] == 2 and stats["hits"] == 4, stats


def test_timeline_tags_cache_hit_vs_negotiated(tmp_path, monkeypatch):
    """Rank 0's timeline marks each dispatch cycle with how its verdict was
    produced (docs/timeline.md)."""
    path = tmp_path / "timeline.json"
    monkeypatch.setenv("HOROVOD_TIMELINE", str(path))
    eng = NativeEngine(0, 1, executor=local_executor, cycle_time_ms=1.0,
                       cache_capacity=8)
    try:
        for _ in range(3):
            eng.synchronize(eng.enqueue("tl.c", np.ones(4, np.float32),
                                        OP_ALLREDUCE))
    finally:
        eng.shutdown()
    text = path.read_text()
    assert "NEGOTIATED" in text       # the populating first pass
    assert "CACHE_HIT" in text        # the cached repeats
    assert "NEGOTIATE_ALLREDUCE" in text  # negotiation span still traced


# ---------------------------------------------------------------------------
# Multi-process coherence (TCP control plane, spawn harness as in
# test_engine.py)
# ---------------------------------------------------------------------------

def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _run_spawn(fn, nprocs=2):
    ctx = multiprocessing.get_context("spawn")
    port = _free_port()
    q = ctx.Queue()
    procs = [ctx.Process(target=fn, args=(r, nprocs, port, q))
             for r in range(nprocs)]
    for p in procs:
        p.start()
    ok = False
    try:
        results = [q.get(timeout=scaled(60)) for _ in procs]
        ok = True
        return results
    finally:
        for p in procs:
            if ok:
                p.join(timeout=scaled(30))
            if p.is_alive():
                p.kill()
                p.join(timeout=10)


def _worker_stream(rank, size, port, q):
    """(a) Stable schedule with a NEW name appearing mid-stream on all
    ranks: miss -> negotiate -> subsequent hits; results stay correct."""
    try:
        eng = NativeEngine(rank, size, executor=local_executor,
                           coordinator_host="127.0.0.1",
                           coordinator_port=port, cycle_time_ms=2.0,
                           cache_capacity=32)
        # local_executor is an identity data plane (as in test_engine.py's
        # TCP tests): each rank sees its own input back.  The cache is a
        # CONTROL-plane feature — what's under test is that every op still
        # completes, in order, with coherent replicas.
        for step in range(4):
            out = eng.synchronize(eng.enqueue(
                "s.a", np.full(4, float(rank), np.float32), OP_ALLREDUCE),
                timeout_s=scaled(30))
            assert out[0] == float(rank), out
            if step >= 2:  # new tensor joins the schedule mid-stream
                out = eng.synchronize(eng.enqueue(
                    "s.b", np.full(2, float(rank), np.float32), OP_ALLREDUCE),
                    timeout_s=scaled(30))
                assert out[0] == float(rank), out
        stats = eng.cache_stats()
        eng.shutdown()
        q.put(("ok", rank, stats))
    except Exception as e:  # noqa: BLE001
        q.put(("err", rank, repr(e)))


def test_new_name_mid_stream_then_hits():
    results = _run_spawn(_worker_stream)
    assert {r[0] for r in results} == {"ok"}, results
    for _, rank, stats in results:
        # s.a: 1 miss + 3 hits; s.b: 1 miss + 1 hit — on every rank.
        assert stats["misses"] == 2, (rank, stats)
        assert stats["hits"] == 4, (rank, stats)


def _worker_coordinated_reshape(rank, size, port, q):
    """(b) All ranks re-announce a cached name with a new shape together:
    coordinated invalidate, clean renegotiation, hits resume."""
    try:
        eng = NativeEngine(rank, size, executor=local_executor,
                           coordinator_host="127.0.0.1",
                           coordinator_port=port, cycle_time_ms=2.0,
                           cache_capacity=32)
        for shape in (3, 5):
            for _ in range(2):
                out = eng.synchronize(eng.enqueue(
                    "r.x", np.full(shape, 1.0, np.float32), OP_ALLREDUCE),
                    timeout_s=scaled(30))
                # local_executor identity data plane; shape/order are the
                # control-plane facts under test.
                assert out.shape == (shape,) and out[0] == 1.0, out
        stats = eng.cache_stats()
        eng.shutdown()
        q.put(("ok", rank, stats))
    except Exception as e:  # noqa: BLE001
        q.put(("err", rank, repr(e)))


def test_coordinated_shape_change_renegotiates():
    results = _run_spawn(_worker_coordinated_reshape)
    assert {r[0] for r in results} == {"ok"}, results
    for _, rank, stats in results:
        assert stats["misses"] == 2 and stats["hits"] == 2, (rank, stats)


def _worker_lone_reshape(rank, size, port, q):
    """(b') ONE rank re-announces a cached name with a different shape: the
    entry is flushed everywhere and the renegotiation surfaces the shape
    mismatch as a coordinated error on every rank — no divergence abort, no
    hang, no rank served from a stale cache."""
    try:
        eng = NativeEngine(rank, size, executor=local_executor,
                           coordinator_host="127.0.0.1",
                           coordinator_port=port, cycle_time_ms=2.0,
                           cache_capacity=32)
        for _ in range(2):  # warm the entry on every rank
            eng.synchronize(eng.enqueue("l.x", np.ones(4, np.float32),
                                        OP_ALLREDUCE), timeout_s=scaled(30))
        x = np.ones(4 + (1 if rank == 0 else 0), np.float32)
        h = eng.enqueue("l.x", x, OP_ALLREDUCE)
        try:
            eng.synchronize(h, timeout_s=scaled(30))
            q.put(("no-error", rank, None))
        except CollectiveError as e:
            q.put(("collective-error", rank, str(e)))
        eng.shutdown()
    except Exception as e:  # noqa: BLE001
        q.put(("err", rank, repr(e)))


def test_lone_shape_change_is_coordinated_error():
    results = _run_spawn(_worker_lone_reshape)
    assert {r[0] for r in results} == {"collective-error"}, results
    assert all("Mismatched shapes" in r[2] for r in results), results


def _worker_mixed_capacity(rank, size, port, q):
    """Misconfigured jobs (one rank with the cache disabled) must degrade to
    full negotiation everywhere, not deadlock bit announcements against full
    requests.  HOROVOD_CACHE_CAPACITY should match across ranks; this pins
    the failure mode when it doesn't."""
    try:
        eng = NativeEngine(rank, size, executor=local_executor,
                           coordinator_host="127.0.0.1",
                           coordinator_port=port, cycle_time_ms=2.0,
                           cache_capacity=32 if rank == 0 else 0)
        for _ in range(3):
            out = eng.synchronize(eng.enqueue(
                "m.x", np.full(4, 1.0, np.float32), OP_ALLREDUCE),
                timeout_s=scaled(30))
            assert out[0] == 1.0, out
        eng.shutdown()
        q.put(("ok", rank, None))
    except Exception as e:  # noqa: BLE001
        q.put(("err", rank, repr(e)))


def test_mismatched_capacity_degrades_not_deadlocks():
    results = _run_spawn(_worker_mixed_capacity)
    assert {r[0] for r in results} == {"ok"}, results


def _worker_verify_interop(rank, size, port, q):
    """(c) HVD_TPU_VERIFY_SCHEDULE=1 interop: the verifier's rolling hashes
    still cross-check on the cache-hit path (the checkpoint stream is
    recorded at enqueue, which the cache does not bypass)."""
    try:
        os.environ["HVD_TPU_VERIFY_SCHEDULE"] = "1"
        os.environ["HVD_TPU_VERIFY_INTERVAL_TICKS"] = "2"
        eng = NativeEngine(rank, size, executor=local_executor,
                           coordinator_host="127.0.0.1",
                           coordinator_port=port, cycle_time_ms=2.0,
                           cache_capacity=32)
        for i in range(6):
            eng.synchronize(eng.enqueue("v.x", np.ones(4, np.float32),
                                        OP_ALLREDUCE), timeout_s=scaled(30))
        stats = eng.cache_stats()
        div = eng.divergence_report()
        eng.shutdown()
        q.put(("ok", rank, (stats["hits"], div)))
    except Exception as e:  # noqa: BLE001
        q.put(("err", rank, repr(e)))


def test_schedule_verifier_cross_checks_cached_path():
    results = _run_spawn(_worker_verify_interop)
    assert {r[0] for r in results} == {"ok"}, results
    for _, rank, (hits, div) in results:
        assert hits >= 4, (rank, hits)         # the schedule WAS cached
        assert div == [], (rank, div)          # and verified clean


# ---------------------------------------------------------------------------
# ThreadSanitizer: concurrent cache-hit enqueues + shutdown (the condvar
# wake-up path; `make -C horovod_tpu/core check` runs this leg)
# ---------------------------------------------------------------------------

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TSAN_CACHE = textwrap.dedent("""
    import numpy as np, threading
    from horovod_tpu.core.engine import NativeEngine, OP_ALLREDUCE
    from horovod_tpu.core.executors import local_executor

    eng = NativeEngine(0, 1, executor=local_executor, cycle_time_ms=1.0,
                       cache_capacity=8)

    def pound(tid):
        # Per-thread names so every iteration past the first is a cache hit
        # racing the cycle condvar, the drain, and the other threads.
        for i in range(30):
            h = eng.enqueue(f"c{tid}", np.ones(16, np.float32), OP_ALLREDUCE)
            eng.synchronize(h)

    ts = [threading.Thread(target=pound, args=(t,)) for t in range(3)]
    for t in ts: t.start()
    for t in ts: t.join()
    assert eng.cache_stats()["hits"] >= 3 * 29, eng.cache_stats()
    eng.shutdown()  # exercises the cycle_cv_ shutdown wake-up under tsan
    print("CACHE TSAN OK", flush=True)
""")


@pytest.mark.tsan
@pytest.mark.slow
def test_cache_tsan_concurrent_hits_and_shutdown():
    core = os.path.join(REPO, "horovod_tpu", "core")
    rc = subprocess.run(["make", "-C", core, "tsan", "-j4"],
                        capture_output=True)
    if rc.returncode != 0 and not os.path.exists(
            os.path.join(core, "libhvdcore_tsan.so")):
        pytest.skip("tsan build unavailable")
    runtime = tsan_runtime()
    if runtime is None:
        pytest.skip("libtsan runtime not installed")
    env = {**os.environ, "PYTHONPATH": REPO,
           "HVD_CORE_LIB": "libhvdcore_tsan.so",
           "LD_PRELOAD": runtime,
           "TSAN_OPTIONS": "report_bugs=1 halt_on_error=0 exitcode=0"}
    proc = subprocess.run([sys.executable, "-c", TSAN_CACHE],
                          capture_output=True, text=True, env=env, cwd=REPO,
                          timeout=scaled(240))
    assert "CACHE TSAN OK" in proc.stdout, proc.stderr[-3000:]
    for chunk in proc.stderr.split("WARNING: ThreadSanitizer")[1:]:
        assert "hvdcore" not in chunk.split("=" * 18)[0], (
            f"tsan race in libhvdcore:\n{chunk[:4000]}")
