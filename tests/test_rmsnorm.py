"""Fused RMSNorm kernels (ops/rmsnorm.py): numerics pinned against the
pure-jnp reference (and flax's nn.RMSNorm), padding paths, and the
per-block dγ partials the caller sums."""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu.ops.rmsnorm import (FusedRMSNorm, rms_norm,
                                     rms_norm_reference)


def _ref_loss(x, scale):
    return jnp.sum(rms_norm_reference(x, scale).astype(jnp.float32) ** 2)


def _fused_loss(x, scale):
    return jnp.sum(rms_norm(x, scale).astype(jnp.float32) ** 2)


@pytest.mark.parametrize("n,e", [(512, 256), (1024, 768), (300, 384)])
def test_forward_matches_reference(hvd, n, e):
    """Includes n=300: the non-multiple-of-block path exercises padding."""
    x = jax.random.normal(jax.random.PRNGKey(0), (n, e), jnp.float32)
    scale = jax.random.normal(jax.random.PRNGKey(1), (e,)) * 0.1 + 1.0
    np.testing.assert_allclose(np.asarray(rms_norm(x, scale)),
                               np.asarray(rms_norm_reference(x, scale)),
                               rtol=1e-5, atol=1e-5)


def test_forward_bf16_dtype(hvd):
    x = jax.random.normal(jax.random.PRNGKey(0), (256, 256), jnp.bfloat16)
    scale = jnp.ones((256,), jnp.float32)
    y = rms_norm(x, scale)
    assert y.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(y, np.float32),
        np.asarray(rms_norm_reference(x, scale), np.float32))


def test_backward_matches_reference(hvd):
    x = jax.random.normal(jax.random.PRNGKey(2), (640, 256), jnp.float32)
    scale = jax.random.normal(jax.random.PRNGKey(3), (256,)) * 0.1 + 1.0
    gx_ref, gs_ref = jax.grad(_ref_loss, argnums=(0, 1))(x, scale)
    gx, gs = jax.grad(_fused_loss, argnums=(0, 1))(x, scale)
    np.testing.assert_allclose(np.asarray(gx), np.asarray(gx_ref),
                               rtol=2e-4, atol=2e-4)
    # dγ accumulates from per-block partial outputs summed by the caller
    # (640 tokens = 2 blocks — both contribute).
    np.testing.assert_allclose(np.asarray(gs), np.asarray(gs_ref),
                               rtol=2e-4, atol=2e-4)


def test_backward_padded_tokens_do_not_pollute_dscale(hvd):
    """n=100 pads to one 512 block; padded dy rows are zero and must not
    contribute to dγ."""
    x = jax.random.normal(jax.random.PRNGKey(4), (100, 128), jnp.float32)
    scale = jnp.ones((128,))
    gs = jax.grad(_fused_loss, argnums=1)(x, scale)
    gs_ref = jax.grad(_ref_loss, argnums=1)(x, scale)
    np.testing.assert_allclose(np.asarray(gs), np.asarray(gs_ref),
                               rtol=2e-4, atol=2e-4)


def test_block_autoscale_with_embed_dim(hvd):
    """The token block shrinks as E grows so the backward working set stays
    inside VMEM (advisor r4: fixed 512 spills at E≳4k), and an explicit
    ``block`` overrides."""
    from horovod_tpu.ops.rmsnorm import _block_tokens

    assert _block_tokens(256) == 512       # small widths keep the max
    assert _block_tokens(4096) < 512       # large widths scale down
    assert _block_tokens(4096) * 4096 * 4 * 10 <= 12 * 1024 * 1024
    assert _block_tokens(16384) >= 8       # floor holds
    assert _block_tokens(4096, block=512) == 512  # escape hatch

    # Numerics are block-size-independent: a wide-E input through the
    # auto-scaled (smaller) block still matches the reference.
    x = jax.random.normal(jax.random.PRNGKey(8), (96, 4096), jnp.float32)
    scale = jnp.ones((4096,))
    np.testing.assert_allclose(np.asarray(rms_norm(x, scale)),
                               np.asarray(rms_norm_reference(x, scale)),
                               rtol=1e-5, atol=1e-5)
    gx, gs = jax.grad(_fused_loss, argnums=(0, 1))(x, scale)
    gx_ref, gs_ref = jax.grad(_ref_loss, argnums=(0, 1))(x, scale)
    np.testing.assert_allclose(np.asarray(gx), np.asarray(gx_ref),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(gs), np.asarray(gs_ref),
                               rtol=2e-4, atol=2e-4)


def test_leading_batch_dims(hvd):
    x = jax.random.normal(jax.random.PRNGKey(5), (4, 96, 256), jnp.float32)
    scale = jnp.ones((256,))
    np.testing.assert_allclose(np.asarray(rms_norm(x, scale)),
                               np.asarray(rms_norm_reference(x, scale)),
                               rtol=1e-5, atol=1e-5)


def test_module_matches_flax_rmsnorm(hvd):
    """FusedRMSNorm (both paths) ≈ nn.RMSNorm, and the parameter structure
    is identical (one 'scale' leaf) so checkpoints interchange."""
    x = jax.random.normal(jax.random.PRNGKey(6), (64, 128), jnp.float32)
    flax_mod = nn.RMSNorm(epsilon=1e-6)
    flax_params = flax_mod.init(jax.random.PRNGKey(7), x)

    for use_fused in (False, True):
        mod = FusedRMSNorm(use_fused=use_fused)
        params = mod.init(jax.random.PRNGKey(7), x)
        assert (jax.tree.structure(params)
                == jax.tree.structure(flax_params))
        got = mod.apply(flax_params, x)  # flax params drive ours directly
        want = flax_mod.apply(flax_params, x)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)


def test_transformer_uses_same_param_structure(hvd):
    """fused_norm True/False produce identical parameter trees for the
    Transformer (resume across the toggle)."""
    from horovod_tpu.models import Transformer, TransformerConfig

    kw = dict(vocab_size=64, num_layers=1, num_heads=2, head_dim=8,
              embed_dim=16, mlp_dim=32, max_seq_len=8)
    tokens = jnp.zeros((1, 8), jnp.int32)
    p_fused = Transformer(TransformerConfig(**kw, fused_norm=True)).init(
        jax.random.PRNGKey(0), tokens)
    p_plain = Transformer(TransformerConfig(**kw, fused_norm=False)).init(
        jax.random.PRNGKey(0), tokens)
    assert jax.tree.structure(p_fused) == jax.tree.structure(p_plain)
    for a, b in zip(jax.tree.leaves(p_fused), jax.tree.leaves(p_plain)):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32))
