"""The ``python -m horovod_tpu.run`` launcher (mpirun -np analog).

Covers the two contracts mpirun gives the reference's users (reference
README.md:148-180): (1) N ranks come up wired together — a cross-process
eager allreduce produces the job-wide sum on every rank; (2) the first
abnormal rank exit aborts the whole job with that exit code instead of
leaving surviving ranks hung.
"""

import os
import subprocess
import sys
import textwrap

from _timing import scaled

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

OK_SCRIPT = textwrap.dedent("""
    import numpy as np
    import horovod_tpu as hvd
    hvd.init()
    h = hvd.allreduce_async(np.full(3, float(hvd.rank() + 1), np.float32),
                            average=False, name="launch.ar")
    out = hvd.synchronize(h)
    expect = hvd.size() * (hvd.size() + 1) / 2
    np.testing.assert_allclose(out, np.full(3, expect))
    print(f"RANK{hvd.rank()} SUM={out[0]:.0f}", flush=True)
""")

CRASH_SCRIPT = textwrap.dedent("""
    import sys, time
    import horovod_tpu as hvd
    hvd.init()
    if hvd.rank() == 1:
        sys.exit(7)
    time.sleep(120)   # must be terminated by the launcher, not run out
""")


def _launch(np_, script, timeout):
    return subprocess.run(
        [sys.executable, "-m", "horovod_tpu.run", "-np", str(np_),
         sys.executable, "-c", script],
        cwd=REPO, capture_output=True, text=True, timeout=timeout)


def test_two_ranks_allreduce_with_tagged_output():
    res = _launch(2, OK_SCRIPT, timeout=scaled(180))
    assert res.returncode == 0, res.stdout + res.stderr
    for rank in (0, 1):
        assert f"[{rank}]: RANK{rank} SUM=3" in res.stdout, res.stdout


def test_crashed_rank_aborts_job_with_its_exit_code():
    res = _launch(2, CRASH_SCRIPT, timeout=scaled(180))
    assert res.returncode == 7, res.stdout + res.stderr
    assert "rank 1 exited with code 7" in res.stderr


def test_rejects_hosts_flag():
    res = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.run", "-np", "2", "-H",
         "a:1,b:1", "true"],
        cwd=REPO, capture_output=True, text=True, timeout=scaled(60))
    assert res.returncode != 0
    assert "pod runtime" in res.stderr
