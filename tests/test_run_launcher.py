"""The ``python -m horovod_tpu.run`` launcher (mpirun -np analog).

Covers the two contracts mpirun gives the reference's users (reference
README.md:148-180): (1) N ranks come up wired together — a cross-process
eager allreduce produces the job-wide sum on every rank; (2) the first
abnormal rank exit aborts the whole job with that exit code instead of
leaving surviving ranks hung.
"""

import os
import subprocess
import sys
import textwrap

from _timing import scaled

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

OK_SCRIPT = textwrap.dedent("""
    import numpy as np
    import horovod_tpu as hvd
    hvd.init()
    h = hvd.allreduce_async(np.full(3, float(hvd.rank() + 1), np.float32),
                            average=False, name="launch.ar")
    out = hvd.synchronize(h)
    expect = hvd.size() * (hvd.size() + 1) / 2
    np.testing.assert_allclose(out, np.full(3, expect))
    print(f"RANK{hvd.rank()} SUM={out[0]:.0f}", flush=True)
""")

CRASH_SCRIPT = textwrap.dedent("""
    import sys, time
    import horovod_tpu as hvd
    hvd.init()
    if hvd.rank() == 1:
        sys.exit(7)
    time.sleep(120)   # must be terminated by the launcher, not run out
""")


def _launch(np_, script, timeout):
    return subprocess.run(
        [sys.executable, "-m", "horovod_tpu.run", "-np", str(np_),
         sys.executable, "-c", script],
        cwd=REPO, capture_output=True, text=True, timeout=timeout)


def test_two_ranks_allreduce_with_tagged_output():
    res = _launch(2, OK_SCRIPT, timeout=scaled(180))
    assert res.returncode == 0, res.stdout + res.stderr
    for rank in (0, 1):
        assert f"[{rank}]: RANK{rank} SUM=3" in res.stdout, res.stdout


def test_crashed_rank_aborts_job_with_its_exit_code():
    res = _launch(2, CRASH_SCRIPT, timeout=scaled(180))
    assert res.returncode == 7, res.stdout + res.stderr
    assert "rank 1 exited with code 7" in res.stderr


def test_rejects_hosts_flag():
    res = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.run", "-np", "2", "-H",
         "a:1,b:1", "true"],
        cwd=REPO, capture_output=True, text=True, timeout=scaled(60))
    assert res.returncode != 0
    assert "pod runtime" in res.stderr


# ---------------------------------------------------------------------------
# Supervision: restarts, crash-loop breaker, process-group signal forwarding
# (docs/fault_tolerance.md).  Children are jax-free so these stay cheap.
# ---------------------------------------------------------------------------

# Fails on the first attempt, succeeds after the supervisor relaunches —
# HVD_TPU_RESTART_ATTEMPT is the launcher-exported attempt counter.
FLAKY_SCRIPT = textwrap.dedent("""
    import os, sys
    attempt = int(os.environ.get("HVD_TPU_RESTART_ATTEMPT", "0"))
    print(f"ATTEMPT={attempt}", flush=True)
    sys.exit(7 if attempt == 0 else 0)
""")

ALWAYS_FAIL_SCRIPT = "import sys; sys.exit(9)"

# Spawns a grandchild, reports its pid, then lingers: SIGTERM to the
# launcher must reap the WHOLE process group, grandchild included.
GRANDCHILD_SCRIPT = textwrap.dedent("""
    import subprocess, sys, time
    p = subprocess.Popen([sys.executable, "-c", "import time; time.sleep(300)"])
    print(f"GRANDCHILD={p.pid}", flush=True)
    for _ in range(1200):
        time.sleep(0.25)
""")


def _supervised(np_, script, *flags, timeout):
    env = {**os.environ, "HVD_TPU_RESTART_BACKOFF": "0.05"}
    return subprocess.run(
        [sys.executable, "-m", "horovod_tpu.run", "-np", str(np_), *flags,
         "--", sys.executable, "-c", script],
        cwd=REPO, capture_output=True, text=True, timeout=timeout, env=env)


# Rank 1 is the originator (exit 7); every other rank lingers and is
# SIGTERM'd by the launcher's job-abort (rc -15 → 143).  Secondary exits
# must never mask the originator in supervision accounting.
ORIGINATOR_SCRIPT = textwrap.dedent("""
    import os, sys, time
    rank = int(os.environ["JAX_PROCESS_ID"])
    attempt = int(os.environ.get("HVD_TPU_RESTART_ATTEMPT", "0"))
    if rank == 1 and attempt == 0:
        time.sleep(0.3)   # let the peers reach their sleep first
        sys.exit(7)
    if attempt > 0:
        sys.exit(0)       # relaunched job runs clean
    time.sleep(120)       # terminated by the launcher, not run out
""")


def test_secondary_sigterm_exits_never_mask_originator():
    """Supervision/restart accounting keys off the ORIGINATING abnormal
    exit: ranks the launcher SIGTERMs afterwards (rc -15 → 143) ride along
    in the same teardown and must not become the recorded job exit code —
    neither in the restart log line nor in the budget-exhausted final
    code."""
    res = _supervised(3, ORIGINATOR_SCRIPT, "--max-restarts", "1",
                      timeout=scaled(60))
    assert res.returncode == 0, res.stdout + res.stderr
    assert "rank 1 exited with code 7" in res.stderr, res.stderr
    # The restart accounting recorded the originator's 7, not a
    # secondary's 143.
    assert "job failed with exit code 7" in res.stderr, res.stderr
    assert "exit code 143" not in res.stderr, res.stderr

    # Without restart budget the job's own exit code is the originator's.
    res = _supervised(3, ORIGINATOR_SCRIPT, timeout=scaled(60))
    assert res.returncode == 7, res.stdout + res.stderr


def test_restart_recovers_flaky_job():
    res = _supervised(2, FLAKY_SCRIPT, "--max-restarts", "2",
                      timeout=scaled(60))
    assert res.returncode == 0, res.stdout + res.stderr
    assert "ATTEMPT=0" in res.stdout and "ATTEMPT=1" in res.stdout
    assert "restarting (attempt 1" in res.stderr, res.stderr


def test_restart_budget_exhausts_with_original_code():
    res = _supervised(1, ALWAYS_FAIL_SCRIPT, "--max-restarts", "1",
                      timeout=scaled(60))
    assert res.returncode == 9, res.stdout + res.stderr
    assert "restart budget exhausted" in res.stderr, res.stderr
    # Exactly one restart was attempted before giving up.
    assert res.stderr.count("restarting (attempt") == 1, res.stderr


def test_no_restart_by_default():
    res = _supervised(1, ALWAYS_FAIL_SCRIPT, timeout=scaled(60))
    assert res.returncode == 9
    assert "restarting" not in res.stderr


# Elastic supervision accounting (docs/fault_tolerance.md "In-place
# recovery"): rank 1 dies on its founding launch but succeeds as a JOIN
# relaunch; the other ranks linger long enough to stay "alive" while the
# single-rank relaunch happens, then exit clean.
ELASTIC_ACCOUNTING_SCRIPT = textwrap.dedent("""
    import os, sys, time
    rank = int(os.environ["JAX_PROCESS_ID"])
    joined = os.environ.get("HVD_TPU_ELASTIC_JOIN") == "1"
    if rank == 1 and not joined:
        time.sleep(0.3)
        sys.exit(75)          # the expelled/aborted-rank exit
    if rank == 1 and joined:
        print("REJOINED attempt="
              + os.environ.get("HVD_TPU_RESTART_ATTEMPT", "?"), flush=True)
        sys.exit(0)
    time.sleep(2.0)           # survivors keep running through the rejoin
    sys.exit(0)
""")


def test_elastic_single_rank_relaunch_accounting_and_breaker_reset():
    """--elastic supervision: a dead non-coordinator rank is relaunched
    ALONE with HVD_TPU_ELASTIC_JOIN=1 (survivors keep running — no job
    teardown, no full restart), the relaunch gets a fresh attempt counter
    so step-keyed injectors stay disarmed, and the supervisor summary
    accounts it separately from full-job restarts."""
    res = _supervised(3, ELASTIC_ACCOUNTING_SCRIPT, "--elastic",
                      "--max-restarts", "1", timeout=scaled(60))
    assert res.returncode == 0, res.stdout + res.stderr
    assert "elastic mode: relaunching only rank 1" in res.stderr, res.stderr
    # The relaunched incarnation carries a bumped attempt counter (faults
    # keyed to attempt 0 must not re-fire inside the rejoin).
    assert "REJOINED attempt=1" in res.stdout, res.stdout
    # Separate accounting: one single-rank relaunch, zero full restarts —
    # and no mpirun-style job abort was triggered.
    assert "supervisor summary: full_restarts=0 single_rank_relaunches=1" \
        in res.stderr, res.stderr
    assert "terminating remaining ranks" not in res.stderr, res.stderr
    assert "restarting (attempt" not in res.stderr, res.stderr


def test_elastic_rank0_death_still_aborts_job():
    """Coordinator failover (PR 7): rank 0 dying under --elastic no longer
    aborts the job — the standby promotes, the dead seat is relaunched
    alone as a joiner, and the supervisor accounts it as a single-rank
    relaunch rather than an mpirun-style full restart.  (Pre-PR-7 this
    test asserted the job-abort + full-restart contract.)"""
    script = textwrap.dedent("""
        import os, sys, time
        rank = int(os.environ["JAX_PROCESS_ID"])
        joined = os.environ.get("HVD_TPU_ELASTIC_JOIN") == "1"
        if rank == 0 and not joined:
            time.sleep(0.3)
            sys.exit(75)
        if rank == 0 and joined:
            print("COORD_SEAT_REJOINED attempt="
                  + os.environ.get("HVD_TPU_RESTART_ATTEMPT", "?"), flush=True)
            sys.exit(0)
        time.sleep(2.0)           # survivor keeps running through failover
        sys.exit(0)
    """)
    res = _supervised(2, script, "--elastic", "--max-restarts", "1",
                      timeout=scaled(60))
    assert res.returncode == 0, res.stdout + res.stderr
    # The job survives: rank 0's seat comes back alone, no job teardown.
    assert "elastic mode: relaunching only rank 0" in res.stderr, res.stderr
    assert "COORD_SEAT_REJOINED attempt=1" in res.stdout, res.stdout
    assert "supervisor summary: full_restarts=0 single_rank_relaunches=1" \
        in res.stderr, res.stderr
    assert "restarting (attempt" not in res.stderr, res.stderr


def test_sigterm_reaps_grandchildren():
    import signal
    import time

    env = {**os.environ}
    p = subprocess.Popen(
        [sys.executable, "-m", "horovod_tpu.run", "-np", "1", "--",
         sys.executable, "-c", GRANDCHILD_SCRIPT],
        cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, env=env)
    try:
        gpid = None
        deadline = time.monotonic() + scaled(30)
        for line in p.stdout:
            if "GRANDCHILD=" in line:
                gpid = int(line.rsplit("=", 1)[1])
                break
            assert time.monotonic() < deadline, "no grandchild line"
        assert gpid is not None
        p.send_signal(signal.SIGTERM)
        p.wait(timeout=scaled(30))
        # The grandchild must be gone: SIGTERM was forwarded to the whole
        # process group (os.killpg), so a preempted supervisor cannot
        # orphan worker subprocesses.
        deadline = time.monotonic() + scaled(10)
        while time.monotonic() < deadline:
            try:
                os.kill(gpid, 0)
            except ProcessLookupError:
                break
            time.sleep(0.1)
        else:
            os.kill(gpid, 9)
            raise AssertionError(f"grandchild {gpid} survived the drain")
    finally:
        if p.poll() is None:
            p.kill()
