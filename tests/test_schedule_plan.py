"""Unit + lowering tests for the trace-time overlap schedule planner
(ops/schedule_plan.py) — ISSUE 9's tentpole.

The planner's contract, pinned here:

* width-1 bypass (the r5 −4.3% ResNet headline regression: chaining where
  psum is identity only constrains the scheduler);
* headroom-deficit degradation — the 468M config's 79 MB OOM must turn
  into a shallower chain (or free-combining fallback) with NO hand-set
  ``HOROVOD_OVERLAP_BUCKETS``;
* explicit overrides (argument, env, custom planner instance) win
  bit-for-bit over the adaptive plan;
* plan stability: the same manifest/width/headroom always produces the
  same plan, across repeated traces.
"""

import numpy as np
import pytest

from horovod_tpu.ops import schedule_plan as sp
from horovod_tpu.utils import env


@pytest.fixture(autouse=True)
def _fresh_planner_state(monkeypatch):
    # Planner decisions must come from THIS test's env, not the shell's;
    # the probe cache and dedup log reset so tests stay order-independent.
    monkeypatch.delenv("HOROVOD_OVERLAP_BUCKETS", raising=False)
    monkeypatch.delenv("HVD_TPU_OVERLAP_BUCKETS", raising=False)
    monkeypatch.delenv("HOROVOD_DEVICE_HEADROOM_MB", raising=False)
    monkeypatch.delenv("HVD_TPU_DEVICE_HEADROOM_MB", raising=False)
    sp._reset_for_tests()
    yield
    sp._reset_for_tests()


def manifest(count=18, bytes_per=2 * 1024 * 1024):
    return sp.GradientManifest(nbytes=(bytes_per,) * count,
                               dtypes=("float32",) * count)


# ---------------------------------------------------------------------------
# AdaptivePlanner policy
# ---------------------------------------------------------------------------

def test_width1_bypasses_chain():
    plan = sp.AdaptivePlanner().plan(manifest(), width=1, headroom_mb=None)
    assert plan.chain_depth == 0 and not plan.chained
    assert "width-1" in plan.reason
    # Bypass even with infinite headroom — width, not memory, is the
    # reason there is nothing to overlap.
    plan = sp.AdaptivePlanner().plan(manifest(), width=1, headroom_mb=1e9)
    assert not plan.chained


def test_real_width_slack_headroom_keeps_default_depth():
    plan = sp.AdaptivePlanner().plan(manifest(), width=8,
                                     headroom_mb=8000.0)
    assert plan.chain_depth == env.DEFAULT_OVERLAP_BUCKETS and plan.chained


def test_unknown_headroom_keeps_default_depth():
    plan = sp.AdaptivePlanner().plan(manifest(), width=8, headroom_mb=None)
    assert plan.chain_depth == env.DEFAULT_OVERLAP_BUCKETS and plan.chained


def test_headroom_deficit_degrades_depth_then_bypasses():
    # The 468M shape: ~936 MB of bf16 gradients.  The depth-4 chain's
    # estimated extra live-range (~88 MB — calibrated to the measured
    # 79 MB OOM, see CHAIN_LIVE_FRACTION) exceeds an 80 MB headroom, so
    # the planner halves the depth; a tiny headroom kills the chain.
    m = sp.GradientManifest(
        nbytes=(936 * 1024 * 1024 // 20,) * 20, dtypes=("bfloat16",) * 20)
    assert sp.chain_extra_bytes(m.total_bytes, 4) > 80 * 1024 * 1024
    degraded = sp.AdaptivePlanner().plan(m, width=16, headroom_mb=80.0)
    assert 1 < degraded.chain_depth < env.DEFAULT_OVERLAP_BUCKETS
    assert sp.chain_extra_bytes(m.total_bytes, degraded.chain_depth) \
        <= 80 * 1024 * 1024
    assert "degraded" in degraded.reason
    dead = sp.AdaptivePlanner().plan(m, width=16, headroom_mb=10.0)
    assert dead.chain_depth == 0 and not dead.chained
    assert "free-combining" in dead.reason


def test_chain_extra_bytes_monotone_and_zero_without_chain():
    total = 936 * 1024 * 1024
    estimates = [sp.chain_extra_bytes(total, d) for d in (8, 4, 2, 1, 0)]
    assert estimates == sorted(estimates, reverse=True)
    assert estimates[-2:] == [0, 0]  # depth <= 1: no chain, no bill


def test_single_tensor_never_chains():
    plan = sp.AdaptivePlanner().plan(manifest(count=1), width=8,
                                     headroom_mb=None)
    assert not plan.chained and plan.chain_depth == 0


# ---------------------------------------------------------------------------
# Overrides beat the adaptive plan
# ---------------------------------------------------------------------------

def test_argument_override_beats_adaptive():
    # overlap_buckets=6 at width 1: legacy semantics chain anyway —
    # bit-for-bit what the knob did before the planner existed.
    t = [np.zeros((8, 8), np.float32)] * 4
    plan = sp.plan_overlap(t, width=1, override=6)
    assert plan.planner == "static" and plan.chain_depth == 6
    assert plan.chained  # width is irrelevant to the static branch
    off = sp.plan_overlap(t, width=8, override=0)
    assert off.planner == "static" and not off.chained


def test_env_override_beats_adaptive(monkeypatch):
    # Legacy-pin fixture on purpose (the planner normally decides).
    monkeypatch.setenv("HOROVOD_OVERLAP_BUCKETS", "5")  # hvd-lint: disable=HVD107
    t = [np.zeros((8, 8), np.float32)] * 4
    plan = sp.plan_overlap(t, width=1, override=None)
    assert plan.planner == "static" and plan.chain_depth == 5


def test_argument_beats_env(monkeypatch):
    monkeypatch.setenv("HOROVOD_OVERLAP_BUCKETS", "5")  # hvd-lint: disable=HVD107
    t = [np.zeros((8, 8), np.float32)] * 4
    plan = sp.plan_overlap(t, width=8, override=2)
    assert plan.chain_depth == 2


def test_custom_planner_instance_wins(monkeypatch):
    monkeypatch.setenv("HOROVOD_OVERLAP_BUCKETS", "5")  # hvd-lint: disable=HVD107

    class Fixed3(sp.Planner):
        name = "fixed3"

        def plan(self, m, width, headroom_mb):
            return sp.BucketPlan(
                planner=self.name, chain_depth=3, width=width,
                tensor_count=m.count, total_bytes=m.total_bytes,
                headroom_mb=headroom_mb, chain_extra_bytes=0,
                reason="test planner")

    t = [np.zeros((8, 8), np.float32)] * 4
    plan = sp.plan_overlap(t, width=8, planner=Fixed3())
    assert plan.planner == "fixed3" and plan.chain_depth == 3


def test_malformed_env_override_degrades_to_static_default(monkeypatch):
    # A typo'd knob stays on the round-5 path (static depth 4 + warning),
    # NOT silently adaptive — set-but-broken must not change semantics.
    import warnings

    monkeypatch.setenv("HOROVOD_OVERLAP_BUCKETS", "four")  # hvd-lint: disable=HVD107
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        t = [np.zeros((8, 8), np.float32)] * 4
        plan = sp.plan_overlap(t, width=1, override=None)
    assert plan.planner == "static"
    assert plan.chain_depth == env.DEFAULT_OVERLAP_BUCKETS
    assert any("HOROVOD_OVERLAP_BUCKETS" in str(w.message) for w in caught)


# ---------------------------------------------------------------------------
# Stability + observability
# ---------------------------------------------------------------------------

def test_plan_stable_across_repeated_traces():
    t = [np.zeros((64, 64), np.float32)] * 8
    plans = [sp.plan_overlap(t, width=8) for _ in range(3)]
    assert plans[0] == plans[1] == plans[2]
    import horovod_tpu as hvd

    last = hvd.overlap_plan()
    assert last == plans[-1].as_dict()
    assert last["chained"] and last["planner"] == "adaptive"


def test_overlap_plan_none_before_any_decision():
    import horovod_tpu as hvd

    assert hvd.overlap_plan() is None


def test_headroom_env_override_wins_and_is_deterministic(monkeypatch):
    monkeypatch.setenv("HVD_TPU_DEVICE_HEADROOM_MB", "50")
    assert sp.probe_headroom_mb() == 50.0
    assert env.device_headroom_mb() == 50.0
    monkeypatch.setenv("HVD_TPU_DEVICE_HEADROOM_MB", "-5")
    assert sp.probe_headroom_mb() == 0.0  # negative clamps to "none left"


def test_headroom_env_malformed_warns_and_probes(monkeypatch):
    import warnings

    monkeypatch.setenv("HVD_TPU_DEVICE_HEADROOM_MB", "lots")
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        assert env.device_headroom_mb() is None
    assert any("HVD_TPU_DEVICE_HEADROOM_MB" in str(w.message)
               for w in caught)


def test_probe_result_is_cached_per_process(monkeypatch):
    # Plan stability across retraces requires one probe answer per
    # process — not a live value that drifts as buffers come and go.
    first = sp.probe_headroom_mb()
    assert sp.probe_headroom_mb() == first
    assert sp._probe_cache == [first]


# ---------------------------------------------------------------------------
# Lowering integration: headroom deficit reshapes the compiled program
# ---------------------------------------------------------------------------

def test_simulated_headroom_deficit_degrades_lowered_chain(monkeypatch):
    # Acceptance: a simulated deficit (HVD_TPU_DEVICE_HEADROOM_MB) makes
    # the planner degrade chain depth in the ACTUAL lowered program, with
    # no hand-set HOROVOD_OVERLAP_BUCKETS anywhere.  The audit model
    # carries ~33.6 MB of gradients -> depth-4 chain bill ≈ 3.01 MB,
    # depth-2 ≈ 2.0 MB: a 3 MB headroom forces exactly one halving.
    import horovod_tpu as hvd

    hvd.init()
    monkeypatch.setenv("HVD_TPU_DEVICE_HEADROOM_MB", "3")
    from examples.overlap_audit import audit_cpu_sim

    audit = audit_cpu_sim()
    plan = audit["plan"]
    assert plan["planner"] == "adaptive", plan
    assert plan["chain_depth"] == 2, plan
    assert plan["headroom_mb"] == 3.0, plan
    # depth 2 -> exactly one inter-bucket gate survives in the stablehlo.
    assert audit["gate_is_finite_ops"] == 1, audit


def test_distributed_optimizer_planner_kwarg_rejected_with_zero1():
    import optax

    import horovod_tpu as hvd
    from horovod_tpu.ops import StaticPlanner

    with pytest.raises(ValueError, match="planner"):
        hvd.DistributedOptimizer(optax.sgd(0.01), sharded_state=True,
                                 planner=StaticPlanner(4))
