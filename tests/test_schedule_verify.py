"""Runtime schedule verifier (HVD_TPU_VERIFY_SCHEDULE; analysis/schedule.py
+ core/src/controller.cc).  Contract under test:

* a deliberately rank-divergent job (rank 1 skips one allreduce) aborts
  with a coordinated CollectiveError carrying the divergence report that
  names the first mismatched collective per rank — within seconds, NOT
  after the 60 s stall-warning window;
* ``divergence_report()`` returns the structured view on every rank (the
  ``stall_report()`` analog);
* an unmodified job runs clean under the verifier (no false positives,
  empty report);
* with the flag off nothing is recorded (zero overhead on the hot path).
"""

import multiprocessing
import os
import socket
import time

import numpy as np
import pytest

from _timing import scaled

from horovod_tpu.analysis.schedule import ScheduleRecorder


# ---------------------------------------------------------------------------
# Recorder unit behaviour (no engine needed)
# ---------------------------------------------------------------------------

def test_rolling_hash_deterministic_and_order_sensitive():
    a, b, c = ScheduleRecorder(), ScheduleRecorder(), ScheduleRecorder()
    ops = [("allreduce", "g0", "float32", (4,)),
           ("allgather", "g1", "int32", (2, 3)),
           ("broadcast", "w", "float32", (8,))]
    for op in ops:
        a.record(*op)
        b.record(*op)
    for op in reversed(ops):
        c.record(*op)
    ha = [h for _, h, _ in a.drain()]
    hb = [h for _, h, _ in b.drain()]
    hc = [h for _, h, _ in c.drain()]
    assert ha == hb                      # same schedule -> same hash chain
    assert ha[-1] != hc[-1]              # same ops, different order -> differ
    assert len(set(ha)) == len(ha)       # chain rolls, never repeats


def test_recorder_distinguishes_metadata():
    a, b = ScheduleRecorder(), ScheduleRecorder()
    a.record("allreduce", "g", "float32", (4,))
    b.record("allreduce", "g", "float16", (4,))
    (_, ha, _), = a.drain()
    (_, hb, _), = b.drain()
    assert ha != hb


def test_record_noop_when_disabled(monkeypatch):
    monkeypatch.delenv("HVD_TPU_VERIFY_SCHEDULE", raising=False)
    monkeypatch.delenv("HOROVOD_VERIFY_SCHEDULE", raising=False)
    from horovod_tpu.analysis import schedule

    before = len(schedule.recorder().drain())
    schedule.record("allreduce", "x", "float32", (4,))
    assert len(schedule.recorder().drain()) == before == 0


# ---------------------------------------------------------------------------
# Two-process engine integration
# ---------------------------------------------------------------------------

def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _worker_divergent(rank, size, port, q):
    os.environ["HVD_TPU_VERIFY_SCHEDULE"] = "1"
    os.environ["HVD_TPU_VERIFY_INTERVAL_TICKS"] = "2"
    # The verifier must beat the stall machinery to the punch: keep the
    # stall window at its (long) default so a pass proves the abort came
    # from divergence detection, not stall escalation.
    try:
        from horovod_tpu.core.engine import (CollectiveError, NativeEngine,
                                             OP_ALLREDUCE)
        from horovod_tpu.core.executors import local_executor

        eng = NativeEngine(rank, size, executor=local_executor,
                           coordinator_host="127.0.0.1",
                           coordinator_port=port, cycle_time_ms=2.0)
        t0 = time.monotonic()
        try:
            handles = []
            for i in range(4):
                if i == 2 and rank == 1:
                    continue  # rank 1 skips one collective: divergence
                handles.append(eng.enqueue(f"step.{i}",
                                           np.ones(4, np.float32),
                                           OP_ALLREDUCE))
            for h in handles:
                eng.synchronize(h, timeout_s=scaled(60))
            q.put(("no-error", rank, None, time.monotonic() - t0))
        except CollectiveError as e:
            q.put(("diverged", rank, str(e), time.monotonic() - t0))
        finally:
            # The rank that SKIPPED the collective has all of its own ops
            # legitimately paired, so it may finish before the divergence
            # verdict lands — the report still must reach it within the
            # verify cadence (never the stall window).
            deadline = time.monotonic() + scaled(30)
            report = eng.divergence_report()
            while not report and time.monotonic() < deadline:
                time.sleep(0.02)
                report = eng.divergence_report()
            q.put(("report", rank, report, None))
            eng._shutdown.set()  # engine already stopped itself
    except Exception as e:  # noqa: BLE001
        q.put(("err", rank, repr(e), None))


def _worker_clean(rank, size, port, q):
    os.environ["HVD_TPU_VERIFY_SCHEDULE"] = "1"
    os.environ["HVD_TPU_VERIFY_INTERVAL_TICKS"] = "2"
    try:
        from horovod_tpu.core.engine import NativeEngine, OP_ALLGATHER, \
            OP_ALLREDUCE
        from horovod_tpu.core.executors import local_executor

        eng = NativeEngine(rank, size, executor=local_executor,
                           coordinator_host="127.0.0.1",
                           coordinator_port=port, cycle_time_ms=2.0)
        outs = []
        for i in range(6):
            h = eng.enqueue(f"t.{i}", np.full(8, rank + 1.0, np.float32),
                            OP_ALLREDUCE)
            outs.append(float(eng.synchronize(h, timeout_s=scaled(60))[0]))
        g = eng.synchronize(eng.enqueue("gather", np.ones((rank + 1, 2),
                                                          np.float32),
                                        OP_ALLGATHER), timeout_s=scaled(60))
        report = eng.divergence_report()
        eng.shutdown()
        q.put(("ok", rank, (outs, g.shape, report), None))
    except Exception as e:  # noqa: BLE001
        q.put(("err", rank, repr(e), None))


def _spawn(fn, nprocs, messages_per_proc=1):
    ctx = multiprocessing.get_context("spawn")
    port = _free_port()
    q = ctx.Queue()
    procs = [ctx.Process(target=fn, args=(r, nprocs, port, q))
             for r in range(nprocs)]
    for p in procs:
        p.start()
    ok = False
    try:
        results = [q.get(timeout=scaled(90))
                   for _ in range(nprocs * messages_per_proc)]
        ok = True
        return results
    finally:
        for p in procs:
            if ok:
                p.join(timeout=scaled(30))
            if p.is_alive():
                p.kill()
                p.join(timeout=10)


def test_divergent_job_aborts_with_report():
    results = _spawn(_worker_divergent, 2, messages_per_proc=2)
    assert not [r for r in results if r[0] == "err"], results
    errors = {r[1]: r for r in results if r[0] == "diverged"}
    reports = [r for r in results if r[0] == "report"]
    # Rank 0 is blocked on the collective rank 1 skipped: it MUST abort
    # with the divergence error instead of hanging to the stall timeout.
    # (Rank 1's own ops all pair up, so it may legitimately complete.)
    assert 0 in errors, results
    _, _, msg, elapsed = errors[0]
    assert "schedule divergence" in msg.lower(), msg
    # The first mismatched collective is named for each rank: rank 0's
    # seq-2 submission is step.2, rank 1's (having skipped it) step.3.
    assert "step.2" in msg and "step.3" in msg, msg
    assert "rank 0" in msg and "rank 1" in msg, msg
    # No stall-timeout wait: detection rides the 2-tick verify cadence.
    assert elapsed < scaled(30), f"took {elapsed}s — stall-timeout-like"
    # The structured report reaches EVERY rank (stall_report analog).
    assert len(reports) == 2, results
    for _, rank, report, _ in reports:
        assert [r for r, _, _ in report] == [0, 1], (rank, report)
        seqs = {s for _, s, _ in report}
        assert seqs == {2}, report       # first mismatched sequence number
        descs = sorted(d for _, _, d in report)
        assert "step.2" in descs[0] and "step.3" in descs[1], report


def test_clean_job_runs_clean_under_verifier():
    results = _spawn(_worker_clean, 2)
    assert {r[0] for r in results} == {"ok"}, results
    for _, rank, (outs, gshape, report), _ in results:
        # local_executor data plane: identity per process — coordination,
        # not arithmetic, is under test here.
        assert outs == [rank + 1.0] * 6, (rank, outs)
        # Ragged allgather (per-rank dim 0) must NOT trip the verifier:
        # dim 0 is excluded from the schedule hash like the coordinator's
        # own trailing-dims-only consistency check.
        assert tuple(gshape) == (rank + 1, 2), (rank, gshape)
        assert report == [], report      # verifier stayed quiet


def test_engine_skips_verify_plumbing_when_disabled(monkeypatch):
    monkeypatch.delenv("HVD_TPU_VERIFY_SCHEDULE", raising=False)
    monkeypatch.delenv("HOROVOD_VERIFY_SCHEDULE", raising=False)
    from horovod_tpu.analysis import schedule
    from horovod_tpu.core.engine import NativeEngine, OP_ALLREDUCE
    from horovod_tpu.core.executors import local_executor

    schedule.recorder().reset()
    eng = NativeEngine(0, 1, executor=local_executor, cycle_time_ms=1.0)
    try:
        assert eng._verify_enabled is False
        eng.synchronize(eng.enqueue("off.t", np.ones(4, np.float32),
                                    OP_ALLREDUCE))
        assert schedule.recorder().drain() == []
        assert eng.divergence_report() == []
    finally:
        eng.shutdown()


def test_verify_enabled_single_process_roundtrip(monkeypatch):
    monkeypatch.setenv("HVD_TPU_VERIFY_SCHEDULE", "1")
    from horovod_tpu.analysis import schedule
    from horovod_tpu.core.engine import NativeEngine, OP_ALLREDUCE
    from horovod_tpu.core.executors import local_executor

    schedule.recorder().reset()
    eng = NativeEngine(0, 1, executor=local_executor, cycle_time_ms=1.0)
    try:
        x = np.arange(6, dtype=np.float32)
        out = eng.synchronize(eng.enqueue("v.t0", x, OP_ALLREDUCE))
        np.testing.assert_array_equal(out, x)
        # Single process trivially agrees with itself: no divergence.
        assert eng.divergence_report() == []
    finally:
        eng.shutdown()
        schedule.recorder().reset()
