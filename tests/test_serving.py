"""Continuous-batching serving engine (serving/, docs/inference.md).

The load-bearing claims, each pinned here:

* **Bit-exactness** — a sequence decoded in a mixed continuous batch
  (including sequences admitted mid-stream into freed slots) produces
  byte-identical tokens AND logits to the same sequence decoded alone
  through the same-shaped program.  This is what makes continuous
  batching safe to default on: every backend op is batch-row-
  independent, and the program shape is fixed by the slot count, not by
  who is active.
* **No recompiles, warm cache** — program shapes come from the slot
  count and the bucket menu only, so the ``serving.tick`` collective is
  one fixed-signature allreduce per step: steady state is all
  response-cache hits (zero NEGOTIATED), asserted from cache_stats().
* **Scheduler semantics** — per-step admission into freed slots (no
  drain barrier), mid-batch eviction of finished/over-length sequences,
  the static-batching baseline barrier, and the stats surface
  (``hvd.serving_stats()``).
* **Prefix cache** — decode with the radix-trie KV cache ON is bitwise
  identical to a cold prefill (tokens AND logits), on the stub and on
  the real paged transformer backend; refcounted pages pin while
  referenced and only refs==0 leaves LRU-evict under pressure.
* **Speculative decoding** — greedy n-gram speculation emits the exact
  plain-decode stream on both the reject path (positional stub: nothing
  ever accepted) and the accept path (periodic stub: fewer steps, same
  tokens), and bit-exact tokens on the real transformer.

The chaos soak (grow + SIGKILL under load, serving/soak.py) runs under
``-m slow``; SERVING_SOAK_REPS repeats it.
"""

from __future__ import annotations

import os
import socket

import numpy as np
import pytest

from horovod_tpu.serving import engine as engine_mod
from horovod_tpu.serving.engine import (Request, ServingConfig,
                                        ServingEngine, StubBackend,
                                        serving_stats)

jax = pytest.importorskip("jax")
jnp = jax.numpy


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# ---------------------------------------------------------------------------
# StubBackend scheduler semantics (jax-free path, the soak fleet's unit)
# ---------------------------------------------------------------------------

def test_stub_stream_is_deterministic():
    from horovod_tpu.serving.worker import (completion_crc,
                                            expected_completion)

    eng = ServingEngine(StubBackend(2), ServingConfig(
        num_slots=2, buckets=(8,), max_seq_len=64))
    prompt = [3, 1, 4, 1, 5]
    req = eng.submit(prompt, 6)
    done = eng.run_until_idle()
    assert [r.rid for r in done] == [req.rid]
    assert done[0].tokens == expected_completion(prompt, 6)
    assert completion_crc(done[0].tokens) == completion_crc(
        expected_completion(prompt, 6))
    assert done[0].finish_reason == "max_new_tokens"


def test_continuous_admission_backfills_freed_slots():
    # 2 slots, 4 requests: the short pair finishes first and the waiting
    # pair is admitted into the freed slots while the batch keeps
    # decoding — no drain barrier.
    eng = ServingEngine(StubBackend(2), ServingConfig(
        num_slots=2, buckets=(8,), max_seq_len=64))
    for _ in range(2):
        eng.submit([1, 2], 2)
    for _ in range(2):
        eng.submit([3, 4], 8)
    eng.step()  # both shorts admitted (prefill token #1)
    assert eng.counters["admitted"] == 2 and len(eng.queue) == 2
    eng.step()  # shorts hit max_new=2 and evict; longs admitted next step
    eng.step()
    assert eng.counters["admitted"] == 4
    assert eng.counters["evicted"] >= 2
    done = eng.run_until_idle()
    assert eng.counters["completed"] == 4
    assert all(r.finish_reason == "max_new_tokens"
               for r in done) or eng.counters["completed"] == 4


def test_static_batching_holds_admissions_until_drain():
    eng = ServingEngine(StubBackend(2), ServingConfig(
        num_slots=2, buckets=(8,), max_seq_len=64, static_batching=True))
    eng.submit([1], 3)
    eng.submit([2], 3)
    eng.submit([3], 3)
    eng.step()
    assert eng.counters["admitted"] == 2  # batch formed...
    eng.step()
    assert eng.counters["admitted"] == 2  # ...and the barrier holds
    eng.run_until_idle()
    assert eng.counters["completed"] == 3


def test_over_length_evicted_mid_batch():
    eng = ServingEngine(StubBackend(1), ServingConfig(
        num_slots=1, buckets=(8,), max_seq_len=10))
    req = eng.submit([1, 2, 3, 4, 5, 6], 100)  # 6 + 100 >> max_seq_len
    done = eng.run_until_idle()
    assert done[0].rid == req.rid
    assert done[0].finish_reason == "max_seq_len"
    assert len(done[0].tokens) == 4  # 6 prompt + 4 generated = 10


def test_unbucketable_prompt_rejected_not_queued():
    eng = ServingEngine(StubBackend(1), ServingConfig(
        num_slots=1, buckets=(8,), max_seq_len=64))
    req = eng.submit(list(range(9)), 4)  # > max bucket
    assert req.state == "DONE" and req.finish_reason == "rejected"
    assert not eng.queue and eng.counters["rejected"] == 1
    # Not silent: the error names the limit hit and the knob that
    # raises it, so the caller can act without reading engine source.
    assert req.error is not None and "9 tokens" in req.error
    assert "HVD_TPU_SERVE_BUCKETS" in req.error
    assert "HVD_TPU_SERVE_MAX_LEN" in req.error
    assert eng.stats()["rejected"] == 1


def test_eos_finishes_early():
    eng = ServingEngine(StubBackend(1), ServingConfig(
        num_slots=1, buckets=(8,), max_seq_len=64, eos_id=(1 + 2 + 2) % 256))
    req = eng.submit([1, 2], 50)  # first token = (sum+len) % 256 = eos
    done = eng.run_until_idle()
    assert done[0].rid == req.rid and done[0].finish_reason == "eos"
    assert len(done[0].tokens) == 1


def test_serving_stats_accessor(monkeypatch):
    monkeypatch.setattr(engine_mod, "_ACTIVE", None)
    zero = serving_stats()
    assert set(zero) == set(engine_mod._STATS_KEYS)
    assert all(v == 0 for v in zero.values())
    eng = ServingEngine(StubBackend(2), ServingConfig(
        num_slots=2, buckets=(8,), max_seq_len=64))
    eng.submit([1, 2, 3], 4)
    eng.run_until_idle()
    live = serving_stats()  # lazy hvd.serving_stats resolves to this
    assert live["completed"] == 1 and live["tokens"] == 4
    assert live["steps"] == eng.counters["steps"]
    assert live["ttft_p50_ms"] >= 0.0 and live["active_slots"] == 0


def test_completions_survive_aborted_tick():
    # A reconfiguration aborts the serving.tick allreduce with
    # MembershipChanged AFTER the step's evictions.  The completion must
    # still reach on_complete (the worker's DONE line — the soak's
    # no-lost-request proof) and the step() return value must not vanish:
    # it is parked and handed over by the next successful step.
    from horovod_tpu.core.engine import MembershipChanged

    class _FlakyCollective:
        def __init__(self):
            self.blow = False

        def timeline_instant(self, *a, **k):
            pass

        def enqueue(self, name, vec, op):
            if self.blow:
                self.blow = False
                raise MembershipChanged("reconfig mid-tick")
            return "h"

        def synchronize(self, h):
            return np.zeros(9, np.float32)

    coll = _FlakyCollective()
    seen: list[Request] = []
    eng = ServingEngine(StubBackend(1), ServingConfig(
        num_slots=1, buckets=(8,), max_seq_len=64), collective=coll,
        on_complete=seen.append)
    req = eng.submit([1, 2, 3], 1)  # completes on its admission step
    coll.blow = True
    with pytest.raises(MembershipChanged):
        eng.step()
    assert [r.rid for r in seen] == [req.rid]  # delivered before the tick
    assert eng._active_count() == 0  # evicted — the slot really freed
    nxt = eng.submit([4, 5], 1)
    done = eng.step()  # post-reconfigure step flushes the parked request
    assert [r.rid for r in done] == [req.rid, nxt.rid]
    assert [r.rid for r in seen] == [req.rid, nxt.rid]  # no double DONE


# ---------------------------------------------------------------------------
# TransformerBackend: the real-model KV-cache decode path
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def small_model():
    from horovod_tpu.models.transformer import (Transformer,
                                                TransformerConfig)

    cfg = TransformerConfig(vocab_size=64, num_layers=2, num_heads=2,
                            head_dim=8, embed_dim=16, mlp_dim=32,
                            max_seq_len=64, dtype=jnp.float32,
                            logits_dtype=jnp.float32)
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))
    return model, params, cfg


def _make_engine(small_model, num_slots: int, record=True) -> ServingEngine:
    from horovod_tpu.serving.engine import TransformerBackend

    model, params, mcfg = small_model
    backend = TransformerBackend(model, params, mcfg, num_slots,
                                 max_seq_len=64)
    return ServingEngine(backend, ServingConfig(
        num_slots=num_slots, buckets=(8, 16), max_seq_len=64,
        record_logits=record))


def test_prefill_logits_match_full_forward(small_model):
    model, params, _ = small_model
    eng = _make_engine(small_model, num_slots=1)
    prompt = [5, 9, 2, 7, 11, 3]
    req = eng.submit(prompt, 1)
    eng.run_until_idle()
    full = model.apply(params, jnp.asarray([prompt], jnp.int32))
    np.testing.assert_allclose(req.logits[0],
                               np.asarray(full[0, len(prompt) - 1]),
                               rtol=2e-5, atol=2e-5)
    assert req.tokens[0] == int(np.argmax(np.asarray(
        full[0, len(prompt) - 1])))


def test_batched_decode_bit_exact_vs_sequential(small_model):
    # Mixed lengths + a mid-stream admission: rid 3 is submitted only
    # after the batch has been decoding for 3 steps and lands in a freed
    # slot.  Every request's tokens AND per-step logits must be
    # BIT-identical to decoding it alone through the same-shaped program
    # — batch-row independence is the whole safety argument.
    rng = np.random.RandomState(0)
    prompts = [list(map(int, rng.randint(0, 64, n))) for n in (5, 8, 13, 6)]
    max_news = [6, 4, 9, 7]

    eng = _make_engine(small_model, num_slots=3)
    reqs = [eng.submit(p, m) for p, m in zip(prompts[:3], max_news[:3])]
    for _ in range(3):
        eng.step()
    reqs.append(eng.submit(prompts[3], max_news[3]))  # mid-stream
    eng.run_until_idle()

    solo_eng = _make_engine(small_model, num_slots=3)
    for req, prompt, max_new in zip(reqs, prompts, max_news):
        solo = solo_eng.submit(prompt, max_new)
        solo_eng.run_until_idle()
        assert solo.tokens == req.tokens, (prompt, solo.tokens, req.tokens)
        assert len(solo.logits) == len(req.logits)
        for a, b in zip(solo.logits, req.logits):
            assert np.array_equal(a, b), "logits diverged bitwise"


def test_hot_swap_changes_output_without_recompile(small_model):
    model, params, _ = small_model
    eng = _make_engine(small_model, num_slots=2)
    prompt = [9, 1, 9, 1]
    a = eng.submit(prompt, 5)
    eng.run_until_idle()
    zeroed = jax.tree.map(jnp.zeros_like, params)
    eng.backend.swap_params(zeroed)
    b = eng.submit(prompt, 5)
    eng.run_until_idle()
    eng.backend.swap_params(params)
    c = eng.submit(prompt, 5)
    eng.run_until_idle()
    assert a.tokens == c.tokens  # same weights, same stream
    assert a.tokens != b.tokens  # the swap actually took


# ---------------------------------------------------------------------------
# Prefix cache: radix-trie refcounting + bit-exact prefix-attached decode
# ---------------------------------------------------------------------------

def test_prefix_cache_trie_eviction_and_refcount_pinning():
    # Pure-python unit: referenced pages pin, only refs==0 leaves evict,
    # and eviction recycles pages without ever touching a live path.
    from horovod_tpu.serving.prefix_cache import PrefixCache

    pc = PrefixCache(num_slots=2, pages_per_slot=4, cache_pages=2,
                     page_size=4)
    hot = list(range(100, 113))  # 13 tokens -> 3 donated chunks
    a0 = pc.admit(0, hot)
    assert a0.prefix_len == 0 and len(a0.donated) == 3
    assert pc.lookup(hot) == 12
    # A second slot attaches to the donated chunks by reference.
    a1 = pc.admit(1, hot, max_prefix_len=pc.lookup(hot))
    assert a1.prefix_len == 12 and a1.shared == a0.donated
    pc.release(1)
    # Churn distinct prompts through slot 1 until the pool must evict.
    for n in range(8):
        pc.admit(1, [200 + 16 * n + i for i in range(13)])
        pc.release(1)
    assert pc.evictions > 0
    # Slot 0 still holds refs on the hot path: it must have survived
    # every eviction, and a fresh admission still fully shares it.
    assert pc.lookup(hot) == 12
    a2 = pc.admit(1, hot, max_prefix_len=12)
    assert a2.prefix_len == 12 and a2.shared == a0.donated
    pc.release(1)
    pc.release(0)
    # With every ref dropped the hot chunks are evictable in turn.
    for n in range(8):
        pc.admit(0, [600 + 16 * n + i for i in range(13)])
        pc.release(0)
    assert pc.lookup(hot) < 12
    # Conservation: pages never leak — everything resident or free.
    assert pc.resident_pages() + len(pc._free) == pc.num_pages - 1


def _make_paged_engine(small_model, num_slots=2, cache_pages=8):
    from horovod_tpu.serving.engine import PagedTransformerBackend

    model, params, mcfg = small_model
    backend = PagedTransformerBackend(model, params, mcfg, num_slots,
                                      max_seq_len=64,
                                      cache_pages=cache_pages, page_size=8)
    return ServingEngine(backend, ServingConfig(
        num_slots=num_slots, buckets=(8, 16), max_seq_len=64,
        record_logits=True, prefix_cache_pages=cache_pages, page_size=8))


def test_prefix_cache_bit_exact_vs_cold(small_model):
    # Three prompts share a 12-token system prefix.  The first admission
    # donates its chunks; the later two attach to the shared page and
    # prefill only their suffix — while decoding CONCURRENTLY through the
    # same shared page.  Tokens and logits must be bitwise identical to a
    # cold dense engine that re-prefills everything.
    rng = np.random.RandomState(3)
    shared = list(map(int, rng.randint(0, 64, 12)))
    tails = [list(map(int, rng.randint(0, 64, 4))) for _ in range(3)]

    warm = _make_paged_engine(small_model)
    first = warm.submit(shared + tails[0], 6)
    warm.run_until_idle()
    later = [warm.submit(shared + t, 6) for t in tails[1:]]  # same batch
    warm.run_until_idle()
    st = warm.stats()
    assert st["prefix_hits"] == 2 and st["prefix_hit_tokens"] == 16
    assert st["prefix_hit_rate"] > 0.0

    cold = _make_engine(small_model, num_slots=2)
    for req, tail in zip([first] + later, tails):
        solo = cold.submit(shared + tail, 6)
        cold.run_until_idle()
        assert solo.tokens == req.tokens, (tail, solo.tokens, req.tokens)
        for a, b in zip(solo.logits, req.logits):
            assert np.array_equal(a, b), \
                "prefix-attached decode diverged bitwise from cold prefill"


# ---------------------------------------------------------------------------
# Speculative decoding: lossless greedy acceptance, both paths
# ---------------------------------------------------------------------------

def test_spec_decode_reject_path_identical_stream():
    # The positional stub's next token depends on absolute position, so
    # lookahead drafts never verify: speculation must degrade to plain
    # decode with the identical stream, not corrupt it.
    from horovod_tpu.serving.worker import expected_completion

    eng = ServingEngine(StubBackend(2), ServingConfig(
        num_slots=2, buckets=(8,), max_seq_len=64, spec_k=3))
    prompt = [3, 1, 4, 1, 5]
    req = eng.submit(prompt, 8)
    eng.run_until_idle()
    assert req.tokens == expected_completion(prompt, 8)
    st = eng.stats()
    assert st["spec_drafted"] > 0 and st["spec_accepted"] == 0


def test_spec_decode_accept_path_same_tokens_fewer_steps():
    # The periodic stub is predictable, so the n-gram proposer's drafts
    # verify: same tokens as plain decode in strictly fewer steps.
    def make(k):
        return ServingEngine(StubBackend(1, period=4), ServingConfig(
            num_slots=1, buckets=(8,), max_seq_len=64, spec_k=k))

    plain, spec = make(0), make(3)
    prompt = [1, 2, 3]
    a = plain.submit(prompt, 12)
    plain.run_until_idle()
    b = spec.submit(prompt, 12)
    spec.run_until_idle()
    assert a.tokens == b.tokens
    st = spec.stats()
    assert st["spec_accepted"] > 0 and st["spec_accept_rate"] > 0.0
    assert spec.counters["steps"] < plain.counters["steps"]


def test_spec_decode_bit_exact_vs_plain(small_model):
    # Real transformer: greedy speculation emits the exact plain-decode
    # token stream.  Logits ride a different (block-verify) program
    # shape, so they are compared to tolerance, tokens bitwise.
    from horovod_tpu.serving.engine import TransformerBackend

    model, params, mcfg = small_model
    backend = TransformerBackend(model, params, mcfg, 2, max_seq_len=64)
    spec_eng = ServingEngine(backend, ServingConfig(
        num_slots=2, buckets=(8, 16), max_seq_len=64, spec_k=2,
        record_logits=True))
    rng = np.random.RandomState(1)
    prompts = [list(map(int, rng.randint(0, 64, n))) for n in (5, 9)]
    reqs = [spec_eng.submit(p, 7) for p in prompts]
    spec_eng.run_until_idle()
    assert spec_eng.counters["spec_drafted"] > 0

    plain = _make_engine(small_model, num_slots=2)
    for req, prompt in zip(reqs, prompts):
        solo = plain.submit(prompt, 7)
        plain.run_until_idle()
        assert solo.tokens == req.tokens, (prompt, solo.tokens, req.tokens)
        assert len(solo.logits) == len(req.logits)
        for a, b in zip(solo.logits, req.logits):
            np.testing.assert_allclose(a, b, rtol=2e-5, atol=2e-5)


# ---------------------------------------------------------------------------
# Multi-model router + cross-model budget arbitration
# ---------------------------------------------------------------------------

def test_router_routes_least_loaded_and_scores_slo():
    from horovod_tpu.serving.router import ModelSpec, Router

    def make():
        return ServingEngine(StubBackend(2), ServingConfig(
            num_slots=2, buckets=(8,), max_seq_len=64))

    router = Router()
    router.add_model(ModelSpec("chat", slo_ttft_ms=1000.0), [make(), make()])
    router.add_model(ModelSpec("code", slo_ttft_ms=1000.0), [make()])
    with pytest.raises(KeyError):
        router.submit("nope", [1], 1)
    for i in range(6):
        router.submit("chat" if i % 2 else "code", [1, 2, i], 4)
    router.run_until_idle()
    st = router.stats()
    assert st["chat"]["completed"] == 3 and st["code"]["completed"] == 3
    assert st["chat"]["slo_attainment"] == 1.0  # generous SLO, tiny load
    # Least-loaded admission actually spread chat across both replicas.
    assert all(e.counters["completed"] >= 1
               for e in router._engines["chat"])
    # remove_replica never retires the last seat of a model.
    assert router.remove_replica("code") is None
    assert router.remove_replica("chat") is not None


def test_router_autoscaler_pairs_shrink_with_grow_under_budget():
    from horovod_tpu.serving.autoscale import AutoscaleConfig
    from horovod_tpu.serving.router import (ModelSpec, Router,
                                            RouterAutoscaler)

    def make():
        return ServingEngine(StubBackend(2), ServingConfig(
            num_slots=2, buckets=(8,), max_seq_len=64))

    specs = [ModelSpec("chat"), ModelSpec("code")]
    router = Router()
    router.add_model(specs[0], [make()])
    router.add_model(specs[1], [make(), make()])
    for _ in range(20):  # chat is pressured, code fully idle
        router.submit("chat", [1, 2], 4)
    t = [0.0]
    auto = RouterAutoscaler(
        specs, budget=3,
        config=AutoscaleConfig(min_replicas=1, max_replicas=4,
                               queue_high=4.0, idle_s=1.0, cooldown_s=0.0),
        clock=lambda: t[0])
    # Budget full, donor's idle window not yet elapsed: the grow waits.
    assert auto.decide(router) == []
    t[0] += 2.0
    # Now code's policy independently wants to shrink: the paired move
    # migrates its seat to chat without ever exceeding the budget.
    assert auto.decide(router) == [("code", "shrink"), ("chat", "grow")]


# ---------------------------------------------------------------------------
# The serving.tick collective: fleet counters + response-cache warmth
# ---------------------------------------------------------------------------

def test_tick_collective_warm_cache_and_fleet_counters():
    from horovod_tpu.core.engine import NativeEngine
    from horovod_tpu.core.executors import local_executor

    coll = NativeEngine(0, 1, executor=local_executor,
                        coordinator_host="127.0.0.1",
                        coordinator_port=_free_port(), cycle_time_ms=1.0)
    try:
        eng = ServingEngine(StubBackend(2), ServingConfig(
            num_slots=2, buckets=(8,), max_seq_len=64), collective=coll)
        for k in range(5):
            eng.submit([k + 1, k + 2], 6)
        eng.run_until_idle()
        steps = eng.counters["steps"]
        assert steps > 2
        # Fleet aggregate (size 1: equals local counters).
        assert eng.fleet["completed"] == 5.0
        assert eng.fleet["steps"] == float(steps)
        assert eng.fleet["done_replicas"] == 0.0
        # ONE fixed-signature allreduce per tick: the first negotiates,
        # every later one is a response-cache hit — the zero-NEGOTIATED
        # steady state the ISSUE acceptance demands.
        cs = coll.cache_stats()
        assert cs["misses"] <= 1, cs
        assert cs["hits"] >= steps - 1, (cs, steps)
    finally:
        coll.shutdown()


# ---------------------------------------------------------------------------
# Autoscaler policy (pure decision logic; the fleet soak runs under slow)
# ---------------------------------------------------------------------------

def test_autoscaler_grow_shrink_cooldown():
    from horovod_tpu.serving.autoscale import AutoscaleConfig, Autoscaler

    t = [0.0]
    auto = Autoscaler(AutoscaleConfig(min_replicas=1, max_replicas=3,
                                      queue_high=4.0, idle_s=1.0,
                                      cooldown_s=10.0),
                      clock=lambda: t[0])
    assert auto.decide(1, queued=40, active_slots=8) == "grow"
    t[0] += 1.0  # within cooldown: no flapping
    assert auto.decide(2, queued=40, active_slots=8) is None
    t[0] += 20.0
    assert auto.decide(3, queued=400, active_slots=8) is None  # max cap
    for _ in range(60):  # idle long enough to shrink
        t[0] += 0.5
        d = auto.decide(3, queued=0, active_slots=0)
        if d is not None:
            break
    assert d == "shrink"
    t[0] += 100.0
    assert auto.decide(1, queued=0, active_slots=0) is None  # min floor


@pytest.mark.slow
def test_serving_autoscale_soak():
    """Grow under load + SIGKILL mid-traffic + fleet-wide hot swap: no
    accepted request lost or corrupted, weights cloned over the data
    plane with zero disk reads, bounded end to end.  The chaos scenario
    runs with the prefix cache and speculative decoding enabled in every
    worker — the fast paths must not change a single completion CRC (the
    stub's stream is a pure function of the prompt), and a replica dying
    with slots attached to shared pages must not poison survivors'
    retries."""
    from horovod_tpu.serving import soak

    reps = int(os.environ.get("SERVING_SOAK_REPS", "1"))
    for rep in range(reps):
        r = soak.run_fleet(n=3, qps=40.0, duration_s=4.0, kill=True,
                           join=True, swap=(rep % 2 == 0), seed=rep,
                           prefix_cache=True, spec_k=3)
        assert r["lost"] == 0 and r["completed"] == r["accepted"], r
        assert r["join_disk_reads"] == 0, r
        assert r["killed"] == 1, r
