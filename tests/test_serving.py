"""Continuous-batching serving engine (serving/, docs/inference.md).

The load-bearing claims, each pinned here:

* **Bit-exactness** — a sequence decoded in a mixed continuous batch
  (including sequences admitted mid-stream into freed slots) produces
  byte-identical tokens AND logits to the same sequence decoded alone
  through the same-shaped program.  This is what makes continuous
  batching safe to default on: every backend op is batch-row-
  independent, and the program shape is fixed by the slot count, not by
  who is active.
* **No recompiles, warm cache** — program shapes come from the slot
  count and the bucket menu only, so the ``serving.tick`` collective is
  one fixed-signature allreduce per step: steady state is all
  response-cache hits (zero NEGOTIATED), asserted from cache_stats().
* **Scheduler semantics** — per-step admission into freed slots (no
  drain barrier), mid-batch eviction of finished/over-length sequences,
  the static-batching baseline barrier, and the stats surface
  (``hvd.serving_stats()``).

The chaos soak (grow + SIGKILL under load, serving/soak.py) runs under
``-m slow``; SERVING_SOAK_REPS repeats it.
"""

from __future__ import annotations

import os
import socket

import numpy as np
import pytest

from horovod_tpu.serving import engine as engine_mod
from horovod_tpu.serving.engine import (Request, ServingConfig,
                                        ServingEngine, StubBackend,
                                        serving_stats)

jax = pytest.importorskip("jax")
jnp = jax.numpy


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# ---------------------------------------------------------------------------
# StubBackend scheduler semantics (jax-free path, the soak fleet's unit)
# ---------------------------------------------------------------------------

def test_stub_stream_is_deterministic():
    from horovod_tpu.serving.worker import (completion_crc,
                                            expected_completion)

    eng = ServingEngine(StubBackend(2), ServingConfig(
        num_slots=2, buckets=(8,), max_seq_len=64))
    prompt = [3, 1, 4, 1, 5]
    req = eng.submit(prompt, 6)
    done = eng.run_until_idle()
    assert [r.rid for r in done] == [req.rid]
    assert done[0].tokens == expected_completion(prompt, 6)
    assert completion_crc(done[0].tokens) == completion_crc(
        expected_completion(prompt, 6))
    assert done[0].finish_reason == "max_new_tokens"


def test_continuous_admission_backfills_freed_slots():
    # 2 slots, 4 requests: the short pair finishes first and the waiting
    # pair is admitted into the freed slots while the batch keeps
    # decoding — no drain barrier.
    eng = ServingEngine(StubBackend(2), ServingConfig(
        num_slots=2, buckets=(8,), max_seq_len=64))
    for _ in range(2):
        eng.submit([1, 2], 2)
    for _ in range(2):
        eng.submit([3, 4], 8)
    eng.step()  # both shorts admitted (prefill token #1)
    assert eng.counters["admitted"] == 2 and len(eng.queue) == 2
    eng.step()  # shorts hit max_new=2 and evict; longs admitted next step
    eng.step()
    assert eng.counters["admitted"] == 4
    assert eng.counters["evicted"] >= 2
    done = eng.run_until_idle()
    assert eng.counters["completed"] == 4
    assert all(r.finish_reason == "max_new_tokens"
               for r in done) or eng.counters["completed"] == 4


def test_static_batching_holds_admissions_until_drain():
    eng = ServingEngine(StubBackend(2), ServingConfig(
        num_slots=2, buckets=(8,), max_seq_len=64, static_batching=True))
    eng.submit([1], 3)
    eng.submit([2], 3)
    eng.submit([3], 3)
    eng.step()
    assert eng.counters["admitted"] == 2  # batch formed...
    eng.step()
    assert eng.counters["admitted"] == 2  # ...and the barrier holds
    eng.run_until_idle()
    assert eng.counters["completed"] == 3


def test_over_length_evicted_mid_batch():
    eng = ServingEngine(StubBackend(1), ServingConfig(
        num_slots=1, buckets=(8,), max_seq_len=10))
    req = eng.submit([1, 2, 3, 4, 5, 6], 100)  # 6 + 100 >> max_seq_len
    done = eng.run_until_idle()
    assert done[0].rid == req.rid
    assert done[0].finish_reason == "max_seq_len"
    assert len(done[0].tokens) == 4  # 6 prompt + 4 generated = 10


def test_unbucketable_prompt_rejected_not_queued():
    eng = ServingEngine(StubBackend(1), ServingConfig(
        num_slots=1, buckets=(8,), max_seq_len=64))
    req = eng.submit(list(range(9)), 4)  # > max bucket
    assert req.state == "DONE" and req.finish_reason == "rejected"
    assert not eng.queue and eng.counters["rejected"] == 1


def test_eos_finishes_early():
    eng = ServingEngine(StubBackend(1), ServingConfig(
        num_slots=1, buckets=(8,), max_seq_len=64, eos_id=(1 + 2 + 2) % 256))
    req = eng.submit([1, 2], 50)  # first token = (sum+len) % 256 = eos
    done = eng.run_until_idle()
    assert done[0].rid == req.rid and done[0].finish_reason == "eos"
    assert len(done[0].tokens) == 1


def test_serving_stats_accessor(monkeypatch):
    monkeypatch.setattr(engine_mod, "_ACTIVE", None)
    zero = serving_stats()
    assert set(zero) == set(engine_mod._STATS_KEYS)
    assert all(v == 0 for v in zero.values())
    eng = ServingEngine(StubBackend(2), ServingConfig(
        num_slots=2, buckets=(8,), max_seq_len=64))
    eng.submit([1, 2, 3], 4)
    eng.run_until_idle()
    live = serving_stats()  # lazy hvd.serving_stats resolves to this
    assert live["completed"] == 1 and live["tokens"] == 4
    assert live["steps"] == eng.counters["steps"]
    assert live["ttft_p50_ms"] >= 0.0 and live["active_slots"] == 0


def test_completions_survive_aborted_tick():
    # A reconfiguration aborts the serving.tick allreduce with
    # MembershipChanged AFTER the step's evictions.  The completion must
    # still reach on_complete (the worker's DONE line — the soak's
    # no-lost-request proof) and the step() return value must not vanish:
    # it is parked and handed over by the next successful step.
    from horovod_tpu.core.engine import MembershipChanged

    class _FlakyCollective:
        def __init__(self):
            self.blow = False

        def timeline_instant(self, *a, **k):
            pass

        def enqueue(self, name, vec, op):
            if self.blow:
                self.blow = False
                raise MembershipChanged("reconfig mid-tick")
            return "h"

        def synchronize(self, h):
            return np.zeros(9, np.float32)

    coll = _FlakyCollective()
    seen: list[Request] = []
    eng = ServingEngine(StubBackend(1), ServingConfig(
        num_slots=1, buckets=(8,), max_seq_len=64), collective=coll,
        on_complete=seen.append)
    req = eng.submit([1, 2, 3], 1)  # completes on its admission step
    coll.blow = True
    with pytest.raises(MembershipChanged):
        eng.step()
    assert [r.rid for r in seen] == [req.rid]  # delivered before the tick
    assert eng._active_count() == 0  # evicted — the slot really freed
    nxt = eng.submit([4, 5], 1)
    done = eng.step()  # post-reconfigure step flushes the parked request
    assert [r.rid for r in done] == [req.rid, nxt.rid]
    assert [r.rid for r in seen] == [req.rid, nxt.rid]  # no double DONE


# ---------------------------------------------------------------------------
# TransformerBackend: the real-model KV-cache decode path
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def small_model():
    from horovod_tpu.models.transformer import (Transformer,
                                                TransformerConfig)

    cfg = TransformerConfig(vocab_size=64, num_layers=2, num_heads=2,
                            head_dim=8, embed_dim=16, mlp_dim=32,
                            max_seq_len=64, dtype=jnp.float32,
                            logits_dtype=jnp.float32)
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))
    return model, params, cfg


def _make_engine(small_model, num_slots: int, record=True) -> ServingEngine:
    from horovod_tpu.serving.engine import TransformerBackend

    model, params, mcfg = small_model
    backend = TransformerBackend(model, params, mcfg, num_slots,
                                 max_seq_len=64)
    return ServingEngine(backend, ServingConfig(
        num_slots=num_slots, buckets=(8, 16), max_seq_len=64,
        record_logits=record))


def test_prefill_logits_match_full_forward(small_model):
    model, params, _ = small_model
    eng = _make_engine(small_model, num_slots=1)
    prompt = [5, 9, 2, 7, 11, 3]
    req = eng.submit(prompt, 1)
    eng.run_until_idle()
    full = model.apply(params, jnp.asarray([prompt], jnp.int32))
    np.testing.assert_allclose(req.logits[0],
                               np.asarray(full[0, len(prompt) - 1]),
                               rtol=2e-5, atol=2e-5)
    assert req.tokens[0] == int(np.argmax(np.asarray(
        full[0, len(prompt) - 1])))


def test_batched_decode_bit_exact_vs_sequential(small_model):
    # Mixed lengths + a mid-stream admission: rid 3 is submitted only
    # after the batch has been decoding for 3 steps and lands in a freed
    # slot.  Every request's tokens AND per-step logits must be
    # BIT-identical to decoding it alone through the same-shaped program
    # — batch-row independence is the whole safety argument.
    rng = np.random.RandomState(0)
    prompts = [list(map(int, rng.randint(0, 64, n))) for n in (5, 8, 13, 6)]
    max_news = [6, 4, 9, 7]

    eng = _make_engine(small_model, num_slots=3)
    reqs = [eng.submit(p, m) for p, m in zip(prompts[:3], max_news[:3])]
    for _ in range(3):
        eng.step()
    reqs.append(eng.submit(prompts[3], max_news[3]))  # mid-stream
    eng.run_until_idle()

    solo_eng = _make_engine(small_model, num_slots=3)
    for req, prompt, max_new in zip(reqs, prompts, max_news):
        solo = solo_eng.submit(prompt, max_new)
        solo_eng.run_until_idle()
        assert solo.tokens == req.tokens, (prompt, solo.tokens, req.tokens)
        assert len(solo.logits) == len(req.logits)
        for a, b in zip(solo.logits, req.logits):
            assert np.array_equal(a, b), "logits diverged bitwise"


def test_hot_swap_changes_output_without_recompile(small_model):
    model, params, _ = small_model
    eng = _make_engine(small_model, num_slots=2)
    prompt = [9, 1, 9, 1]
    a = eng.submit(prompt, 5)
    eng.run_until_idle()
    zeroed = jax.tree.map(jnp.zeros_like, params)
    eng.backend.swap_params(zeroed)
    b = eng.submit(prompt, 5)
    eng.run_until_idle()
    eng.backend.swap_params(params)
    c = eng.submit(prompt, 5)
    eng.run_until_idle()
    assert a.tokens == c.tokens  # same weights, same stream
    assert a.tokens != b.tokens  # the swap actually took


# ---------------------------------------------------------------------------
# The serving.tick collective: fleet counters + response-cache warmth
# ---------------------------------------------------------------------------

def test_tick_collective_warm_cache_and_fleet_counters():
    from horovod_tpu.core.engine import NativeEngine
    from horovod_tpu.core.executors import local_executor

    coll = NativeEngine(0, 1, executor=local_executor,
                        coordinator_host="127.0.0.1",
                        coordinator_port=_free_port(), cycle_time_ms=1.0)
    try:
        eng = ServingEngine(StubBackend(2), ServingConfig(
            num_slots=2, buckets=(8,), max_seq_len=64), collective=coll)
        for k in range(5):
            eng.submit([k + 1, k + 2], 6)
        eng.run_until_idle()
        steps = eng.counters["steps"]
        assert steps > 2
        # Fleet aggregate (size 1: equals local counters).
        assert eng.fleet["completed"] == 5.0
        assert eng.fleet["steps"] == float(steps)
        assert eng.fleet["done_replicas"] == 0.0
        # ONE fixed-signature allreduce per tick: the first negotiates,
        # every later one is a response-cache hit — the zero-NEGOTIATED
        # steady state the ISSUE acceptance demands.
        cs = coll.cache_stats()
        assert cs["misses"] <= 1, cs
        assert cs["hits"] >= steps - 1, (cs, steps)
    finally:
        coll.shutdown()


# ---------------------------------------------------------------------------
# Autoscaler policy (pure decision logic; the fleet soak runs under slow)
# ---------------------------------------------------------------------------

def test_autoscaler_grow_shrink_cooldown():
    from horovod_tpu.serving.autoscale import AutoscaleConfig, Autoscaler

    t = [0.0]
    auto = Autoscaler(AutoscaleConfig(min_replicas=1, max_replicas=3,
                                      queue_high=4.0, idle_s=1.0,
                                      cooldown_s=10.0),
                      clock=lambda: t[0])
    assert auto.decide(1, queued=40, active_slots=8) == "grow"
    t[0] += 1.0  # within cooldown: no flapping
    assert auto.decide(2, queued=40, active_slots=8) is None
    t[0] += 20.0
    assert auto.decide(3, queued=400, active_slots=8) is None  # max cap
    for _ in range(60):  # idle long enough to shrink
        t[0] += 0.5
        d = auto.decide(3, queued=0, active_slots=0)
        if d is not None:
            break
    assert d == "shrink"
    t[0] += 100.0
    assert auto.decide(1, queued=0, active_slots=0) is None  # min floor


@pytest.mark.slow
def test_serving_autoscale_soak():
    """Grow under load + SIGKILL mid-traffic + fleet-wide hot swap: no
    accepted request lost or corrupted, weights cloned over the data
    plane with zero disk reads, bounded end to end."""
    from horovod_tpu.serving import soak

    reps = int(os.environ.get("SERVING_SOAK_REPS", "1"))
    for rep in range(reps):
        r = soak.run_fleet(n=3, qps=40.0, duration_s=4.0, kill=True,
                           join=True, swap=(rep % 2 == 0), seed=rep)
        assert r["lost"] == 0 and r["completed"] == r["accepted"], r
        assert r["join_disk_reads"] == 0, r
        assert r["killed"] == 1, r
