"""Stall detection: rank 0 must warn about tensors stuck waiting for
missing ranks (reference CheckForStalledTensors, operations.cc:1366-1412,
60 s window; shrunk here via HOROVOD_STALL_WARNING_TIME)."""

import socket
import subprocess
import sys
import textwrap

from _timing import scaled


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


SCRIPT = textwrap.dedent("""
    import sys, time
    import numpy as np
    from horovod_tpu.core.engine import NativeEngine, OP_ALLREDUCE
    from horovod_tpu.core.executors import local_executor

    rank, port = int(sys.argv[1]), int(sys.argv[2])
    eng = NativeEngine(rank, 2, executor=local_executor,
                       coordinator_host="127.0.0.1", coordinator_port=port,
                       cycle_time_ms=2.0)
    if rank == 0:
        # Only rank 0 announces: the tensor can never become ready.
        eng.enqueue("lonely", np.ones(4, np.float32), OP_ALLREDUCE)
    time.sleep(1.2)
    print("ALIVE", flush=True)
    eng._shutdown.set()   # skip graceful shutdown: peer may already be gone
""")


def test_stall_warning():
    port = _free_port()
    env = {"HOROVOD_STALL_WARNING_TIME": "0.3", "PYTHONPATH": "."}
    import os

    env = {**os.environ, **env}
    procs = [
        subprocess.Popen([sys.executable, "-c", SCRIPT, str(r), str(port)],
                         stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                         env=env, text=True, cwd=os.path.dirname(
                             os.path.dirname(os.path.abspath(__file__))))
        for r in range(2)
    ]
    try:
        outs = [p.communicate(timeout=scaled(60)) for p in procs]
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        raise
    assert "ALIVE" in outs[0][0]
    assert "ALIVE" in outs[1][0]
    stderr0 = outs[0][1]
    assert "Stalled op: lonely" in stderr0, stderr0
    assert "missing ranks: 1" in stderr0, stderr0
