"""Stall detection: rank 0 must warn about tensors stuck waiting for
missing ranks (reference CheckForStalledTensors, operations.cc:1366-1412,
60 s window; shrunk here via HOROVOD_STALL_WARNING_TIME) — plus the two
TPU-rebuild extensions: the structured ``stall_report()`` surface and the
warn -> abort escalation (``HVD_TPU_STALL_ABORT_SECONDS``) that turns a
deadlocked job into a restartable exit instead of a hang."""

import os
import socket
import subprocess
import sys
import textwrap

from _timing import scaled

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


SCRIPT = textwrap.dedent("""
    import sys, time
    import numpy as np
    from horovod_tpu.core.engine import NativeEngine, OP_ALLREDUCE
    from horovod_tpu.core.executors import local_executor

    rank, port = int(sys.argv[1]), int(sys.argv[2])
    eng = NativeEngine(rank, 2, executor=local_executor,
                       coordinator_host="127.0.0.1", coordinator_port=port,
                       cycle_time_ms=2.0)
    if rank == 0:
        # Only rank 0 announces: the tensor can never become ready.
        eng.enqueue("lonely", np.ones(4, np.float32), OP_ALLREDUCE)
    time.sleep(1.2)
    print("ALIVE", flush=True)
    if rank == 0:
        print("REPORT", eng.stall_report(), flush=True)
    eng._shutdown.set()   # skip graceful shutdown: peer may already be gone
""")

# Deliberately-deadlocked job under the escalation: rank 0's engine must
# _Exit the process with the restartable code, never run out this loop
# (bounded 0.25 s naps, ~10 s total worst case — no long sleeps).
ABORT_SCRIPT = textwrap.dedent("""
    import sys, time
    import numpy as np
    from horovod_tpu.core.engine import NativeEngine, OP_ALLREDUCE
    from horovod_tpu.core.executors import local_executor

    rank, port = int(sys.argv[1]), int(sys.argv[2])
    eng = NativeEngine(rank, 2, executor=local_executor,
                       coordinator_host="127.0.0.1", coordinator_port=port,
                       cycle_time_ms=2.0)
    if rank == 0:
        eng.enqueue("wedged", np.ones(4, np.float32), OP_ALLREDUCE)
        for _ in range(40):
            time.sleep(0.25)
        print("SURVIVED", flush=True)   # must never be reached on rank 0
    else:
        # Outlive the coordinator's abort so the job's death is rank 0's.
        for _ in range(8):
            time.sleep(0.25)
    eng._shutdown.set()
""")


def _run_pair(script, port, extra_env):
    env = {**os.environ, "PYTHONPATH": ".", **extra_env}
    procs = [
        subprocess.Popen([sys.executable, "-c", script, str(r), str(port)],
                         stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                         env=env, text=True, cwd=REPO)
        for r in range(2)
    ]
    try:
        outs = [p.communicate(timeout=scaled(60)) for p in procs]
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        raise
    return procs, outs


def test_stall_warning_and_report():
    procs, outs = _run_pair(SCRIPT, _free_port(),
                            {"HOROVOD_STALL_WARNING_TIME": "0.3"})
    assert "ALIVE" in outs[0][0]
    assert "ALIVE" in outs[1][0]
    stderr0 = outs[0][1]
    assert "Stalled op: lonely" in stderr0, stderr0
    assert "missing ranks: 1" in stderr0, stderr0
    # Structured surface of the same condition (hvd.stall_report()).
    assert "REPORT [('lonely', [1])]" in outs[0][0], outs[0][0]


def test_stall_escalates_to_restartable_abort():
    procs, outs = _run_pair(
        ABORT_SCRIPT, _free_port(),
        {"HOROVOD_STALL_WARNING_TIME": "0.2",
         "HVD_TPU_STALL_ABORT_SECONDS": "0.6"})
    # The coordinator aborts the deadlocked job with the distinct
    # restartable exit code (75 = EX_TEMPFAIL) instead of hanging.
    assert procs[0].returncode == 75, (procs[0].returncode, outs[0])
    assert "SURVIVED" not in outs[0][0]
    assert "HVD_TPU_STALL_ABORT_SECONDS" in outs[0][1], outs[0][1]
