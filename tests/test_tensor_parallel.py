"""Tensor-parallel layers: sharded MLP == unsharded math; composes with the
data axis on a 2-D (hvd, tp) mesh."""

import jax
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from horovod_tpu.parallel import ParallelMLP


def test_parallel_mlp_matches_dense(hvd):
    devs = np.array(jax.devices()[:4])
    mesh = Mesh(devs, ("tp",))
    model = ParallelMLP(hidden=32, features=8, axis_name="tp")
    x = jax.random.normal(jax.random.PRNGKey(0), (6, 8))

    def init_and_apply(x):
        params = model.init(jax.random.PRNGKey(1), x)
        return model.apply(params, x), params

    # Per-chip params differ (each holds a shard); correctness check is that
    # the function is linear-consistent: y(2x) for the row+psum pipeline of
    # a linear (no-bias-effect) graph relates as expected.  Simplest strong
    # check: run with tp=1 semantics by comparing against a manual gather.
    out, params = jax.shard_map(
        init_and_apply, mesh=mesh, in_specs=P(), out_specs=(P(), P("tp")),
        check_vma=False)(x)

    # Reconstruct full weights.  out_specs=P("tp") stacks each leaf's shards
    # along dim 0: up kernel arrives as (4·in, local) row blocks; up bias as
    # the concatenated (hidden,); down kernel as (4·in/4, out) = already the
    # full row-parallel kernel; down bias as 4 identical copies.
    pk = params["params"]
    in_dim = x.shape[-1]
    up_k = np.concatenate(
        [np.asarray(pk["up"]["kernel"][i * in_dim:(i + 1) * in_dim])
         for i in range(4)], axis=-1)
    up_b = np.asarray(pk["up"]["bias"])
    down_k = np.asarray(pk["down"]["kernel"])
    down_b = np.asarray(pk["down"]["bias"][:8])[:model.features]
    h = jax.nn.gelu(np.asarray(x) @ up_k + up_b)
    ref = h @ down_k + down_b
    np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)

    # Shards must be DISTINCT (per-shard RNG folding): identical copies
    # would collapse the effective hidden width to hidden/K.
    blocks = [np.asarray(pk["up"]["kernel"][i * in_dim:(i + 1) * in_dim])
              for i in range(4)]
    for i in range(1, 4):
        assert not np.allclose(blocks[0], blocks[i])


def test_tp_with_data_axis(hvd):
    devs = np.array(jax.devices()).reshape(2, 4)
    mesh = Mesh(devs, ("hvd", "tp"))
    model = ParallelMLP(hidden=16, features=4, axis_name="tp")
    x = jax.random.normal(jax.random.PRNGKey(0), (8, 4))

    def fwd(x):
        params = model.init(jax.random.PRNGKey(1), x)
        y = model.apply(params, x)
        # data-parallel mean over the hvd axis composes with tp
        return jax.lax.pmean(y, "hvd")

    out = jax.shard_map(fwd, mesh=mesh, in_specs=P("hvd"), out_specs=P("hvd"),
                        check_vma=False)(x)
    assert out.shape == (8, 4)
    assert np.isfinite(np.asarray(out)).all()
