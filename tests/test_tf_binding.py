"""TensorFlow binding tests — mirrors the reference TF matrix
(reference test/test_tensorflow.py + test/test_keras.py): collectives
round-trip, gradients of all three ops, IndexedSlices sparse path,
compression, tf.function compatibility, DistributedOptimizer /
DistributedGradientTape training, broadcast_variables, keras callbacks
(metric averaging, warmup, momentum correction), load_model round-trip."""

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")
import keras  # noqa: E402

import horovod_tpu.tensorflow as hvd_tf  # noqa: E402
import horovod_tpu.tensorflow.keras as hvd_keras  # noqa: E402


@pytest.fixture()
def hvdtf(hvd):
    # hvd fixture ensures init (single process, 8 virtual chips)
    return hvd_tf


def test_allreduce_roundtrip(hvdtf):
    x = tf.constant([[1.0, 2.0], [3.0, 4.0]])
    out = hvdtf.allreduce(x, average=True)
    np.testing.assert_allclose(out.numpy(), x.numpy())
    out = hvdtf.allreduce(x, average=False)
    np.testing.assert_allclose(out.numpy(), x.numpy() * hvdtf.size())


def test_allreduce_bf16(hvdtf):
    x = tf.cast(tf.linspace(-2.0, 2.0, 8), tf.bfloat16)
    out = hvdtf.allreduce(x, average=False)
    assert out.dtype == tf.bfloat16
    np.testing.assert_allclose(tf.cast(out, tf.float32).numpy(),
                               tf.cast(x, tf.float32).numpy())


def test_allreduce_fp16_compression(hvdtf):
    x = tf.linspace(-1.0, 1.0, 8)
    out = hvdtf.allreduce(x, average=False,
                          compression=hvd_tf.Compression.fp16)
    assert out.dtype == tf.float32
    np.testing.assert_allclose(out.numpy(), x.numpy(), atol=1e-2)


def test_allreduce_int8_wire(hvdtf):
    x = tf.linspace(-1.0, 1.0, 8)
    out = hvdtf.allreduce(x, average=False,
                          compression=hvd_tf.Compression.int8)
    assert out.dtype == tf.float32
    np.testing.assert_allclose(out.numpy(), x.numpy())


def test_int8_error_feedback_carrier(hvdtf):
    """The eager int8 EF carrier quantizes on the engine grid, carries the
    residual (cumulative shipped ≈ cumulative true within one grid step),
    resets on non-finite, and passes through untouched in graph mode."""
    from horovod_tpu.tensorflow import _Int8ErrorFeedback

    ef = _Int8ErrorFeedback()
    g = tf.constant([0.3, -0.7, 1.0])
    s = 1.0 / 127  # engine scale for amax=1.0
    shipped = ef.ship("k", g)
    np.testing.assert_allclose(
        shipped.numpy(),
        np.clip(np.round(g.numpy() / s), -127, 127) * s, rtol=1e-6)
    total = shipped.numpy().astype(np.float64)
    for _ in range(50):
        total += ef.ship("k", g).numpy()
    # Error feedback: 51 identical steps drift by at most ~one grid step
    # total, not 51 accumulated rounding errors.
    np.testing.assert_allclose(total, 51 * g.numpy().astype(np.float64),
                               atol=2 * s)

    bad = tf.constant([np.nan, 1.0, 2.0])
    out = ef.ship("k", bad)
    assert np.isnan(out.numpy()).any()
    assert not np.any(ef._residuals["k"].numpy())

    ef2 = _Int8ErrorFeedback()

    @tf.function
    def graph_ship(x):
        return ef2.ship("g", x)

    x = tf.constant([0.3, 0.7])
    np.testing.assert_array_equal(graph_ship(x).numpy(), x.numpy())
    assert "g" not in ef2._residuals


def test_allreduce_int_average_truncates(hvdtf):
    x = tf.constant([3, 5], tf.int32)
    out = hvdtf.allreduce(x, average=True)
    assert out.dtype == tf.int32
    np.testing.assert_array_equal(out.numpy(), [3, 5])  # size 1


def test_allgather_and_broadcast(hvdtf):
    x = tf.reshape(tf.range(6, dtype=tf.float32), (2, 3))
    np.testing.assert_allclose(hvdtf.allgather(x).numpy(), x.numpy())
    np.testing.assert_allclose(hvdtf.broadcast(x, 0).numpy(), x.numpy())


def test_broadcast_scalar(hvdtf):
    s = tf.constant(5.0)
    out = hvdtf.broadcast(s, 0)
    assert out.shape == ()
    assert float(out) == 5.0


def test_allreduce_grad(hvdtf):
    v = tf.Variable([1.0, 2.0])
    with tf.GradientTape() as tape:
        y = hvdtf.allreduce(v, average=True)
        loss = tf.reduce_sum(y * y)
    g = tape.gradient(loss, v)
    # d/dv sum((v)^2) = 2v at size 1
    np.testing.assert_allclose(g.numpy(), 2 * v.numpy())


def test_allgather_grad(hvdtf):
    v = tf.Variable([[1.0], [2.0]])
    with tf.GradientTape() as tape:
        y = hvdtf.allgather(v)
        loss = tf.reduce_sum(3.0 * y)
    g = tape.gradient(loss, v)
    # grad = allreduce(dy) sliced back to the local rows = 3s
    np.testing.assert_allclose(g.numpy(), np.full((2, 1), 3.0))


def test_broadcast_grad(hvdtf):
    v = tf.Variable([1.0, 2.0, 3.0])
    with tf.GradientTape() as tape:
        y = hvdtf.broadcast(v, 0)
        loss = tf.reduce_sum(y)
    g = tape.gradient(loss, v)
    # rank 0 == root keeps the reduced grad (reference mpi_ops.py:167-182)
    np.testing.assert_allclose(g.numpy(), np.ones(3))


def test_sparse_indexed_slices(hvdtf):
    s = tf.IndexedSlices(values=tf.constant([[1.0, 2.0], [3.0, 4.0]]),
                         indices=tf.constant([0, 2]),
                         dense_shape=tf.constant([4, 2]))
    out = hvdtf.allreduce(s, average=True)
    assert isinstance(out, tf.IndexedSlices)
    np.testing.assert_allclose(out.values.numpy(), s.values.numpy())
    np.testing.assert_array_equal(out.indices.numpy(), s.indices.numpy())


def test_tf_function_graph_mode(hvdtf):
    @tf.function
    def fused(a, b):
        return hvdtf.allreduce(a, average=False), hvdtf.allreduce(
            b, average=False)

    a, b = fused(tf.constant([1.0]), tf.constant([2.0, 3.0]))
    np.testing.assert_allclose(a.numpy(), [1.0])
    np.testing.assert_allclose(b.numpy(), [2.0, 3.0])


def test_distributed_gradient_tape(hvdtf):
    v = tf.Variable([2.0])
    with hvd_tf.DistributedGradientTape(tf.GradientTape()) as tape:
        loss = tf.reduce_sum(v * v)
    g = tape.gradient(loss, [v])
    np.testing.assert_allclose(g[0].numpy(), [4.0])


def test_tape_int8_ef_survives_tape_recreation(hvdtf):
    """EF residuals must carry across DistributedGradientTape instances: a
    tf.GradientTape is one-shot, so the canonical loop rebuilds the wrapper
    every step.  Regression for the round-3 advisor finding (instance-held
    residuals made EF inert in exactly that loop): every wrapper must share
    the one process-wide carrier, and residuals shipped through one
    wrapper's carrier must be visible to the next."""
    import gc

    from horovod_tpu.tensorflow import _TAPE_EF

    v = tf.Variable([0.3, -0.7, 1.0])
    key = _TAPE_EF.key_for(v, 0)
    assert key == id(v)  # identity-keyed, not .ref() (which would pin v)
    _TAPE_EF._residuals.pop(key, None)
    g = tf.constant([0.3, -0.7, 1.0])
    total = np.zeros(3, np.float64)
    for _ in range(40):
        # Fresh wrapper each iteration — the canonical per-step usage.
        # (At size()==1 tape.gradient skips the allreduce+EF path
        # entirely, so drive the wrapper's carrier directly.)
        tape = hvd_tf.DistributedGradientTape(
            tf.GradientTape(persistent=True),
            compression=hvd_tf.Compression.int8)
        assert tape._ef is _TAPE_EF, (
            "wrapper holds a private EF carrier — residuals die with the "
            "one-shot tape")
        total += tape._ef.ship(key, g).numpy().astype(np.float64)
    assert key in _TAPE_EF._residuals, (
        "residuals did not persist in the process-wide carrier")
    # With carried residuals, 40 identical steps drift by at most ~one
    # grid step total — not 40 accumulated rounding errors.
    s = 1.0 / 127
    np.testing.assert_allclose(
        total, 40 * np.array([0.3, -0.7, 1.0], np.float64), atol=2 * s)
    # Discarding the model must release its residual (weakref eviction) —
    # a long-lived process training many models must not accumulate them.
    del v, tape
    gc.collect()
    assert key not in _TAPE_EF._residuals
    assert key not in _TAPE_EF._finalizers

    # Position-keyed (non-variable) sources embed shape+dtype in the key,
    # and ship() resets rather than crashing on a stale mismatched entry.
    t2 = tf.constant([[1.0, 2.0]])
    k2 = _TAPE_EF.key_for(t2, 0)
    assert k2 == (0, (1, 2), "float32")
    _TAPE_EF._residuals[k2] = tf.zeros([3])  # stale different-shape entry
    out = _TAPE_EF.ship(k2, t2)
    assert out.shape == t2.shape
    _TAPE_EF._residuals.pop(k2, None)


def test_broadcast_variables(hvdtf):
    v1 = tf.Variable([1.0, 2.0])
    v2 = tf.Variable(3.0)
    hvdtf.broadcast_variables([v1, v2], root_rank=0)
    np.testing.assert_allclose(v1.numpy(), [1.0, 2.0])
    assert float(v2) == 3.0


def test_broadcast_object(hvdtf):
    obj = {"epoch": 3, "best": 0.91}
    assert hvdtf.broadcast_object(obj, root_rank=0) == obj


def test_broadcast_global_variables_eager_raises(hvdtf):
    with pytest.raises(RuntimeError, match="broadcast_variables"):
        hvdtf.broadcast_global_variables(0)


def _model_and_data(seed=0):
    np.random.seed(seed)
    keras.utils.set_random_seed(seed)
    x = np.random.rand(128, 8).astype("float32")
    y = (x.sum(1) > 4).astype("int32")
    model = keras.Sequential([keras.layers.Dense(16, activation="relu"),
                              keras.layers.Dense(2)])
    return model, x, y


def test_keras_distributed_optimizer_trains(hvdtf):
    model, x, y = _model_and_data()
    opt = hvd_keras.DistributedOptimizer(keras.optimizers.SGD(0.1))
    model.compile(optimizer=opt, loss="sparse_categorical_crossentropy",
                  jit_compile=False)
    hist = model.fit(x, y, epochs=3, batch_size=32, verbose=0)
    assert hist.history["loss"][-1] < hist.history["loss"][0]


def test_keras_embedding_sparse_path(hvdtf):
    keras.utils.set_random_seed(0)
    model = keras.Sequential([keras.layers.Embedding(50, 8),
                              keras.layers.Flatten(),
                              keras.layers.Dense(1)])
    model.compile(
        optimizer=hvd_keras.DistributedOptimizer(keras.optimizers.SGD(0.1)),
        loss="mse", jit_compile=False)
    xi = np.random.randint(0, 50, (64, 4))
    yi = np.random.rand(64, 1).astype("float32")
    hist = model.fit(xi, yi, epochs=2, batch_size=16, verbose=0)
    assert hist.history["loss"][-1] <= hist.history["loss"][0]


def test_keras_sparse_as_dense(hvdtf):
    keras.utils.set_random_seed(0)
    model = keras.Sequential([keras.layers.Embedding(20, 4),
                              keras.layers.Flatten(),
                              keras.layers.Dense(1)])
    model.compile(
        optimizer=hvd_keras.DistributedOptimizer(
            keras.optimizers.SGD(0.1), sparse_as_dense=True),
        loss="mse", jit_compile=False)
    xi = np.random.randint(0, 20, (32, 4))
    yi = np.random.rand(32, 1).astype("float32")
    model.fit(xi, yi, epochs=1, batch_size=16, verbose=0)


def test_keras_callbacks_fit(hvdtf):
    model, x, y = _model_and_data()
    opt = hvd_keras.DistributedOptimizer(
        keras.optimizers.SGD(0.1, momentum=0.9))
    model.compile(optimizer=opt, loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"], jit_compile=False)
    cbs = [hvd_keras.callbacks.BroadcastGlobalVariablesCallback(0),
           hvd_keras.callbacks.MetricAverageCallback(),
           hvd_keras.callbacks.LearningRateWarmupCallback(
               warmup_epochs=2),
           hvd_keras.callbacks.LearningRateScheduleCallback(
               multiplier=0.5, start_epoch=2)]
    hist = model.fit(x, y, epochs=3, batch_size=32, callbacks=cbs, verbose=0)
    # schedule epoch applies initial_lr * 0.5 (size==1 so warmup is flat)
    assert hist.history["lr"][-1] == pytest.approx(0.05, rel=1e-5)


def test_momentum_correction_scales_velocity(hvdtf):
    model, x, y = _model_and_data()
    opt = hvd_keras.DistributedOptimizer(
        keras.optimizers.SGD(0.1, momentum=0.9))
    model.compile(optimizer=opt, loss="sparse_categorical_crossentropy",
                  jit_compile=False)
    model.fit(x, y, epochs=1, batch_size=32, verbose=0)  # builds velocity
    cb = hvd_keras.callbacks.LearningRateScheduleCallback(
        multiplier=0.5, momentum_correction=True)
    cb.set_model(model)
    cb.on_train_begin()
    before = [v.numpy().copy() for v in model.optimizer.momentums]
    assert any(np.abs(b).sum() > 0 for b in before)
    cb._adjust_learning_rate(epoch=0)
    after = [v.numpy() for v in model.optimizer.momentums]
    for b, a in zip(before, after):
        np.testing.assert_allclose(a, b * 0.5, rtol=1e-6)


def test_keras_load_model_rewraps_optimizer(hvdtf, tmp_path):
    model, x, y = _model_and_data()
    opt = hvd_keras.DistributedOptimizer(keras.optimizers.SGD(0.1))
    model.compile(optimizer=opt, loss="sparse_categorical_crossentropy",
                  jit_compile=False)
    model.fit(x, y, epochs=1, batch_size=32, verbose=0)
    path = str(tmp_path / "model.keras")
    model.save(path)
    loaded = hvd_keras.load_model(path)
    assert type(loaded.optimizer).__name__ == "DistributedSGD"
    # resumed training still goes through the allreduce path
    loaded.fit(x, y, epochs=1, batch_size=32, verbose=0)
    np.testing.assert_allclose(loaded.predict(x[:4], verbose=0).shape,
                               (4, 2))


def test_v1_distributed_optimizer_wraps(hvdtf):
    base = tf.compat.v1.train.GradientDescentOptimizer(0.1)
    opt = hvd_tf.DistributedOptimizer(base)
    assert opt.get_slot_names() == base.get_slot_names()


def test_allgather_scalar_grad(hvdtf):
    v = tf.Variable(2.0)
    with tf.GradientTape() as tape:
        y = hvdtf.allgather(v)
        loss = tf.reduce_sum(y)
    g = tape.gradient(loss, v)
    assert g.shape == ()
    assert float(g) == 1.0


def test_alltoall_identity(hvdtf):
    import tensorflow as tf

    x = tf.reshape(tf.range(12, dtype=tf.float32), (4, 3))
    out = hvdtf.alltoall(x)
    np.testing.assert_array_equal(out.numpy(), x.numpy())
    out = hvdtf.alltoall(x, splits=[4])
    np.testing.assert_array_equal(out.numpy(), x.numpy())


def test_host_plane_limitation_documented():
    """The tf.py_function bridge is not serializable/XLA-compilable; the
    wrappers users reach for must say so where they'll see it."""
    import horovod_tpu.tensorflow as hvd_tf

    for fn in (hvd_tf.DistributedOptimizer, hvd_tf.DistributedGradientTape):
        doc = fn.__doc__ or ""
        assert "py_function" in doc and "SavedModel" in doc, fn.__name__


def test_ef_key_for_keras_variable(hvdtf):
    """The keras apply path keys residuals through key_for with keras
    Variables (not tf.Variable): identity-keyed via weakref when possible,
    with eviction on collection."""
    import gc

    import keras

    from horovod_tpu.tensorflow import _Int8ErrorFeedback

    ef = _Int8ErrorFeedback()
    v = keras.Variable(np.ones(3, np.float32))
    key = ef.key_for(v, 0)
    if isinstance(key, int) and key == id(v):
        # weakref-able keras variable: identity key + finalizer eviction
        ef._residuals[key] = tf.zeros(3)
        del v
        gc.collect()
        assert key not in ef._residuals
        assert key not in ef._finalizers
    else:
        # non-weakref-able fallback: position+shape+dtype tuple
        assert key[0] == 0 and tuple(key[1]) == (3,)
