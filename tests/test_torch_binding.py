"""Torch binding tests — mirrors the reference torch matrix
(reference test/test_torch.py): collectives round-trip, in-place variants,
async+fused, DistributedOptimizer trains, broadcast_parameters /
broadcast_optimizer_state restore state, grad of allreduce is allreduce."""

import pytest
import torch

import horovod_tpu.torch as hvd_torch


@pytest.fixture()
def hvdt(hvd):
    # hvd fixture ensures init (single process, 8 virtual chips)
    return hvd_torch


def test_allreduce_roundtrip(hvdt):
    x = torch.arange(12, dtype=torch.float32).reshape(3, 4)
    out = hvdt.allreduce(x, average=True)
    torch.testing.assert_close(out, x)


def test_allreduce_inplace(hvdt):
    x = torch.ones(5)
    ref = x.clone()
    out = hvdt.allreduce_(x, average=False)
    assert out is x
    torch.testing.assert_close(x, ref)


def test_allreduce_bf16(hvdt):
    x = torch.linspace(-2, 2, 8, dtype=torch.bfloat16)
    out = hvdt.allreduce(x, average=False)
    assert out.dtype == torch.bfloat16
    torch.testing.assert_close(out.float(), x.float())


def test_allreduce_fp16_compression(hvdt):
    x = torch.linspace(-1, 1, 8)
    out = hvdt.allreduce(x, average=False,
                         compression=hvd_torch.Compression.fp16)
    assert out.dtype == torch.float32
    torch.testing.assert_close(out, x, atol=1e-2, rtol=1e-2)


def test_allreduce_int8_wire(hvdt):
    """int8 wire routes through the native engine's quantized path (exact
    at size 1: the local executor is an identity)."""
    x = torch.linspace(-1, 1, 8)
    out = hvdt.allreduce(x, average=False,
                         compression=hvd_torch.Compression.int8)
    assert out.dtype == torch.float32
    torch.testing.assert_close(out, x)


def test_allreduce_grad(hvdt):
    x = torch.ones(4, requires_grad=True)
    y = hvdt.allreduce(x, average=True)
    y.sum().backward()
    # grad(allreduce) = allreduce of ones = ones (size 1)
    torch.testing.assert_close(x.grad, torch.ones(4))


def test_async_fused_many(hvdt):
    handles = [hvdt.allreduce_async(torch.full((10,), float(i)),
                                    average=False, name=f"torch.ar{i}")
               for i in range(8)]
    for i, h in enumerate(handles):
        out = hvdt.synchronize(h)
        torch.testing.assert_close(out, torch.full((10,), float(i)))


def test_allgather_broadcast(hvdt):
    x = torch.arange(6).reshape(2, 3)
    torch.testing.assert_close(hvdt.allgather(x), x)
    torch.testing.assert_close(hvdt.broadcast(x, root_rank=0), x)
    y = torch.zeros(3)
    hvdt.broadcast_(y, root_rank=0)


def test_distributed_optimizer_trains(hvdt):
    torch.manual_seed(0)
    model = torch.nn.Sequential(torch.nn.Linear(4, 16), torch.nn.ReLU(),
                                torch.nn.Linear(16, 2))
    opt = hvd_torch.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=0.1),
        named_parameters=model.named_parameters())
    x = torch.randn(32, 4)
    y = (x.sum(dim=1) > 0).long()
    losses = []
    for _ in range(10):
        opt.zero_grad()
        loss = torch.nn.functional.cross_entropy(model(x), y)
        loss.backward()
        opt.step()
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_step_without_backward_no_deadlock(hvdt):
    model = torch.nn.Linear(2, 2)
    opt = hvd_torch.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=0.1),
        named_parameters=model.named_parameters())
    opt.step()  # reference test_force_allreduce: must not hang


def test_backward_passes_per_step(hvdt):
    model = torch.nn.Linear(2, 1)
    opt = hvd_torch.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=0.1),
        named_parameters=model.named_parameters(),
        backward_passes_per_step=2)
    x = torch.randn(4, 2)
    for _ in range(2):  # two accumulation passes, then step
        model(x).sum().backward()
    opt.step()
    opt.zero_grad()


def test_duplicate_named_parameters_rejected(hvdt):
    model = torch.nn.Linear(2, 2)
    params = list(model.named_parameters())
    with pytest.raises(ValueError, match="duplicate"):
        hvd_torch.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.1),
            named_parameters=params + params)


def test_broadcast_parameters_state_dict(hvdt):
    model = torch.nn.Linear(3, 3)
    before = {k: v.clone() for k, v in model.state_dict().items()}
    hvd_torch.broadcast_parameters(model.state_dict(), root_rank=0)
    for k, v in model.state_dict().items():
        torch.testing.assert_close(v, before[k])


def test_broadcast_optimizer_state(hvdt):
    model = torch.nn.Linear(3, 3)
    opt = torch.optim.SGD(model.parameters(), lr=0.25, momentum=0.9)
    model(torch.randn(2, 3)).sum().backward()
    opt.step()
    hvd_torch.broadcast_optimizer_state(opt, root_rank=0)
    assert opt.param_groups[0]["lr"] == pytest.approx(0.25)
    assert opt.param_groups[0]["momentum"] == pytest.approx(0.9)
    # momentum buffers survive the round-trip
    st = opt.state_dict()["state"]
    assert any("momentum_buffer" in s and s["momentum_buffer"] is not None
               for s in st.values())


def test_broadcast_object(hvdt):
    obj = {"epoch": 3, "best": 0.91}
    assert hvd_torch.broadcast_object(obj, root_rank=0) == obj


def test_allgather_grad(hvdt):
    # grad(allgather) = allreduce of the gathered grad, narrowed to this
    # rank's dim-0 segment (reference HorovodAllgather backward,
    # mpi_ops.py:236-254).  Single process: identity on the upstream grad.
    x = torch.arange(6, dtype=torch.float32).reshape(3, 2).requires_grad_()
    y = hvdt.allgather(x)
    (y * torch.arange(6.).reshape(3, 2)).sum().backward()
    torch.testing.assert_close(x.grad, torch.arange(6.).reshape(3, 2))


def test_broadcast_grad_root(hvdt):
    # Rank 0 IS the root here, so the summed grad lands intact (reference
    # HorovodBroadcast backward zeroes it off-root, mpi_ops.py:318-332).
    x = torch.ones(4, requires_grad=True)
    y = hvdt.broadcast(x, root_rank=0)
    (y * 3.0).sum().backward()
    torch.testing.assert_close(x.grad, torch.full((4,), 3.0))


def test_allreduce_sparse_roundtrip(hvdt):
    dense = torch.zeros(6, 3)
    dense[1] = 2.0
    dense[4] = -1.0
    sp = dense.to_sparse_coo()
    out = hvdt.allreduce(sp, average=True)
    assert out.is_sparse
    torch.testing.assert_close(out.to_dense(), dense)


@pytest.mark.parametrize("comp,vdtype", [
    ("fp16", torch.float32), ("bf16", torch.float32),
    ("int8", torch.float32), ("int8", torch.float16),
    ("int8", torch.bfloat16), ("none", torch.float64),
])
def test_allreduce_sparse_compression_matrix(hvdt, comp, vdtype):
    """Sparse values ride the compressed wire (fp16/bf16 cast, or int8 with
    per-rank scales) instead of always-native dtypes — the embedding-path
    wire saving the dense path already had."""
    compression = getattr(hvdt.Compression, comp)
    dense = torch.zeros(8, 4, dtype=vdtype)
    dense[2] = torch.arange(4, dtype=vdtype) * 0.25
    dense[5] = -1.5
    sp = dense.to_sparse_coo()
    out = hvdt.allreduce(sp, average=True, compression=compression)
    assert out.is_sparse
    tol = 1e-2 if comp in ("fp16", "bf16", "int8") else 1e-6
    torch.testing.assert_close(out.to_dense().float(), dense.float(),
                               atol=tol, rtol=tol)


def test_sparse_int8_nan_propagates(hvdt):
    """A non-finite sparse gradient ships q=0 under a non-finite scale, so
    the dequantized values are NaN — overflow is never laundered."""
    dense = torch.zeros(4, 2)
    dense[1] = float("nan")
    out = hvdt.allreduce(dense.to_sparse_coo(), average=False,
                         compression=hvdt.Compression.int8)
    assert not torch.isfinite(out.to_dense()[1]).all()


def test_distributed_optimizer_sparse_embedding(hvdt):
    # nn.Embedding(sparse=True) gradients must route through the
    # gather-based sparse path automatically (reference routes IndexedSlices
    # the same way inside DistributedOptimizer, tensorflow/__init__.py:67-78).
    torch.manual_seed(0)
    emb = torch.nn.Embedding(10, 4, sparse=True)
    opt = hvdt.DistributedOptimizer(
        torch.optim.SGD(emb.parameters(), lr=0.5),
        named_parameters=emb.named_parameters())
    ids = torch.tensor([1, 3, 3, 7])
    before = emb.weight.detach().clone()
    loss = emb(ids).pow(2).sum()
    loss.backward()
    assert emb.weight.grad.is_sparse
    opt.step()
    # rows 1, 3, 7 moved; all others untouched
    moved = (emb.weight.detach() - before).abs().sum(dim=1) > 0
    assert moved[1] and moved[3] and moved[7]
    assert not moved[0] and not moved[9]
    # and training actually descends
    opt.zero_grad()
    loss2 = emb(ids).pow(2).sum()
    assert float(loss2) < float(loss)


def test_distributed_optimizer_sparse_as_dense(hvdt):
    torch.manual_seed(0)
    emb = torch.nn.Embedding(8, 4, sparse=True)
    opt = hvdt.DistributedOptimizer(
        torch.optim.SGD(emb.parameters(), lr=0.1),
        named_parameters=emb.named_parameters(), sparse_as_dense=True)
    loss = emb(torch.tensor([2, 5])).sum()
    loss.backward()
    opt.step()  # grad was densified before the allreduce
    assert not emb.weight.grad.is_sparse


def test_alltoall_identity(hvdt):
    x = torch.arange(12.).reshape(4, 3)
    torch.testing.assert_close(hvdt.alltoall(x), x)
    torch.testing.assert_close(hvdt.alltoall(x, splits=torch.tensor([4])), x)


def test_allgather_object(hvdt):
    assert hvdt.allgather_object({"rank": 0, "v": [1, 2]}) == [
        {"rank": 0, "v": [1, 2]}]
