"""DistributedOptimizer + broadcast tests.

Mirrors the reference's optimizer/broadcast test matrix: gradient averaging
equals local math (reference test_torch.py:175-223 fused/async),
broadcast_parameters restores divergent state (test_torch.py:734-866),
broadcast_object round-trips scalars (torch/__init__.py:197-247 semantics).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P


def test_distributed_optimizer_averages_grads(hvd):
    n = hvd.num_chips()
    opt = hvd.DistributedOptimizer(optax.sgd(0.1))
    params = {"w": jnp.ones((8, 4)), "b": jnp.zeros((4,))}

    @hvd.shard(in_specs=(P(), hvd.batch_spec(2)), out_specs=P())
    def step(params, x):
        def loss(p):
            return jnp.sum((x @ p["w"] + p["b"]) ** 2) / x.shape[0]
        grads = jax.grad(loss)(params)
        state = opt.init(params)
        updates, _ = opt.update(grads, state, params)
        return optax.apply_updates(params, updates)

    x = jax.random.normal(jax.random.PRNGKey(0), (n * 2, 8))

    # Single-worker math on the full batch must equal the distributed result,
    # because averaging shard-mean gradients == full-batch mean gradient.
    def loss_full(p):
        return jnp.sum((x @ p["w"] + p["b"]) ** 2) / (x.shape[0] / n)
    g = jax.grad(lambda p: loss_full(p) / n)(params)
    ref = optax.apply_updates(params, optax.sgd(0.1).update(g, optax.sgd(0.1).init(params), params)[0])

    out = step(params, x)
    np.testing.assert_allclose(out["w"], ref["w"], rtol=1e-5)
    np.testing.assert_allclose(out["b"], ref["b"], rtol=1e-5)


def test_distributed_optimizer_eager_single_process(hvd):
    # Eager path: size()==1 in tests, so update must equal the wrapped one.
    opt = hvd.DistributedOptimizer(optax.adam(1e-3))
    params = {"w": jnp.ones((4,))}
    grads = {"w": jnp.full((4,), 2.0)}
    state = opt.init(params)
    updates, _ = opt.update(grads, state, params)
    ref_opt = optax.adam(1e-3)
    ref_updates, _ = ref_opt.update(grads, ref_opt.init(params), params)
    np.testing.assert_allclose(updates["w"], ref_updates["w"], rtol=1e-6)


def test_broadcast_parameters_in_mesh(hvd):
    @hvd.shard(in_specs=hvd.batch_spec(1), out_specs=P())
    def sync(x):
        # Each worker holds a different param shard value; root 2's value wins.
        return hvd.broadcast(x[0], root_rank=2)

    vals = jnp.arange(hvd.num_chips(), dtype=jnp.float32)
    out = sync(vals)
    assert float(out) == 2.0


def test_broadcast_parameters_pytree(hvd):
    tree = {"a": jnp.ones((3,)), "b": {"c": jnp.zeros((2, 2))}}
    out = hvd.broadcast_parameters(tree, root_rank=0)
    assert jax.tree.structure(out) == jax.tree.structure(tree)
    np.testing.assert_array_equal(out["a"], tree["a"])


def test_broadcast_optimizer_state(hvd):
    opt = optax.sgd(0.1, momentum=0.9)
    state = opt.init({"w": jnp.ones((4,))})
    out = hvd.broadcast_optimizer_state(state, root_rank=0)
    assert jax.tree.structure(jax.tree.map(np.asarray, out)) == \
        jax.tree.structure(jax.tree.map(np.asarray, state))


def test_broadcast_object(hvd):
    obj = {"epoch": 7, "name": "ckpt"}
    assert hvd.broadcast_object(obj, root_rank=0) == obj


def test_scale_learning_rate(hvd):
    assert hvd.scale_learning_rate(0.1) == pytest.approx(0.1 * hvd.num_chips())


def test_accumulate_gradients_matches_full_batch(hvd):
    """Mean-reduced loss ⇒ accumulated microbatch grads == full-batch grads
    (the backward_passes_per_step contract, reference torch/__init__.py:62-112)."""
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(16, 4).astype(np.float32))
    y = jnp.asarray(rng.randint(0, 3, 16).astype(np.int32))
    params = {"w": jnp.asarray(rng.randn(4, 3).astype(np.float32))}

    def grad_fn(p, batch):
        xb, yb = batch

        def loss_fn(p):
            return optax.softmax_cross_entropy_with_integer_labels(
                xb @ p["w"], yb).mean()

        return jax.value_and_grad(loss_fn)(p)

    full_loss, full_grads = grad_fn(params, (x, y))
    for n_mb in (1, 2, 4):
        loss, grads = hvd.accumulate_gradients(grad_fn, params, (x, y), n_mb)
        np.testing.assert_allclose(float(loss), float(full_loss), rtol=1e-5)
        np.testing.assert_allclose(grads["w"], full_grads["w"], rtol=1e-5)


def test_accumulate_gradients_inside_sharded_step(hvd):
    """Composes with DistributedOptimizer under hvd.shard: microbatch mean
    then chip-average equals the global full-batch gradient."""
    n = hvd.num_chips()
    x = jnp.arange(8 * n, dtype=jnp.float32).reshape(-1, 1)

    @hvd.shard(in_specs=hvd.batch_spec(2), out_specs=P())
    def step(xb):
        params = {"w": jnp.ones((1,))}

        def grad_fn(p, mb):
            loss = jnp.mean((mb[:, 0] * p["w"][0]) ** 2)
            return loss, jax.grad(lambda q: jnp.mean(
                (mb[:, 0] * q["w"][0]) ** 2))(p)

        _, grads = hvd.accumulate_gradients(grad_fn, params, xb, 4)
        return hvd.allreduce(grads["w"], average=True)

    got = step(x)
    want = np.mean(2 * np.arange(8 * n, dtype=np.float32) ** 2)
    np.testing.assert_allclose(np.asarray(got)[0], want, rtol=1e-5)


def test_accumulate_gradients_validates(hvd):
    def grad_fn(p, b):
        return jnp.sum(b), p

    with pytest.raises(ValueError, match="divisible"):
        hvd.accumulate_gradients(grad_fn, {"w": jnp.ones(1)},
                                 jnp.ones((10, 2)), 3)
    with pytest.raises(ValueError, match=">= 1"):
        hvd.accumulate_gradients(grad_fn, {"w": jnp.ones(1)},
                                 jnp.ones((10, 2)), 0)


def test_accumulate_gradients_has_aux(hvd):
    """grad_fn from value_and_grad(..., has_aux=True) returns
    ((loss, aux), grads); aux accumulates and averages alongside."""
    x = jnp.arange(8.0).reshape(4, 2)
    params = {"w": jnp.ones((2,))}

    def grad_fn(p, xb):
        def loss_fn(p):
            pred = xb @ p["w"]
            return jnp.mean(pred ** 2), jnp.sum(pred)

        return jax.value_and_grad(loss_fn, has_aux=True)(p)

    (loss, aux), grads = hvd.accumulate_gradients(grad_fn, params, x, 2)
    (floss, faux), fgrads = grad_fn(params, x)
    np.testing.assert_allclose(float(loss), float(floss), rtol=1e-6)
    # aux is averaged over microbatches: per-mb sums average to half the
    # full-batch sum here
    np.testing.assert_allclose(float(aux), float(faux) / 2, rtol=1e-6)
    np.testing.assert_allclose(grads["w"], fgrads["w"], rtol=1e-6)


def test_master_weights_tracks_f32_training(hvd):
    """bf16-resident params + f32 master must track pure-f32 adamw training:
    the master copy evolves EXACTLY like f32 training on the same (bf16-
    rounded) gradients, and resident params land on bf16(master) each step."""
    import ml_dtypes

    key = jax.random.PRNGKey(0)
    w32 = jax.random.normal(key, (16, 8), jnp.float32) * 0.1
    params16 = {"w": w32.astype(jnp.bfloat16)}
    params32 = {"w": params16["w"].astype(jnp.float32)}  # same start point

    inner = optax.adamw(1e-2)
    mw = hvd.master_weights(inner)
    s16 = mw.init(params16)
    s32 = inner.init(params32)

    x = jax.random.normal(jax.random.PRNGKey(1), (4, 16))

    for i in range(10):
        # Identical bf16 gradients feed both paths (the wrapper upcasts).
        g16 = jax.grad(lambda p: jnp.sum(
            (x.astype(jnp.bfloat16) @ p["w"]) ** 2).astype(jnp.float32))(
            params16)
        g32 = {"w": g16["w"].astype(jnp.float32)}

        u16, s16 = mw.update(g16, s16, params16)
        assert u16["w"].dtype == jnp.bfloat16  # delta emitted in param dtype
        params16 = optax.apply_updates(params16, u16)

        u32, s32 = inner.update(g32, s32, params32)
        params32 = optax.apply_updates(params32, u32)

        # master == the f32 training trajectory, bit-for-bit
        np.testing.assert_array_equal(np.asarray(s16.master["w"]),
                                      np.asarray(params32["w"]))
        # resident params land on bf16(master) (1-ulp slack for the rare
        # non-Sterbenz delta-add; exact in practice)
        np.testing.assert_allclose(
            np.asarray(params16["w"], np.float32),
            np.asarray(s16.master["w"]).astype(ml_dtypes.bfloat16)
            .astype(np.float32),
            rtol=0.008, atol=4e-5)


def test_master_weights_requires_params(hvd):
    mw = hvd.master_weights(optax.sgd(0.1))
    p = {"w": jnp.ones(3, jnp.bfloat16)}
    s = mw.init(p)
    assert s.master["w"].dtype == jnp.float32
    with pytest.raises(ValueError, match="master_weights requires params"):
        mw.update({"w": jnp.zeros(3, jnp.bfloat16)}, s)


def test_master_weights_composes_with_distributed_optimizer(hvd):
    """hvd.DistributedOptimizer(hvd.master_weights(adamw)) inside a sharded
    step: bf16 grads ride the wire, master update is averaged-gradient
    exact."""
    n = hvd.num_chips()
    opt = hvd.DistributedOptimizer(hvd.master_weights(optax.sgd(0.1)))
    params = {"w": jnp.ones((8, 4), jnp.bfloat16)}

    @hvd.shard(in_specs=(P(), hvd.batch_spec(2)), out_specs=P())
    def step(params, x):
        def loss(p):
            return jnp.sum((x.astype(jnp.bfloat16) @ p["w"]).astype(
                jnp.float32) ** 2) / x.shape[0]
        grads = jax.grad(loss)(params)
        state = opt.init(params)
        updates, _ = opt.update(grads, state, params)
        return optax.apply_updates(params, updates)

    x = jax.random.normal(jax.random.PRNGKey(0), (n * 2, 8), jnp.float32)
    out = step(params, x)
    assert out["w"].dtype == jnp.bfloat16
    assert not np.array_equal(np.asarray(out["w"], np.float32),
                              np.ones((8, 4), np.float32))


def test_master_weights_composes_with_int8_ef(hvd):
    """The full mixed-precision + compressed-wire stack in one optimizer:
    DistributedOptimizer(master_weights(adamw), compression=int8).  Pins
    that the three state layers coexist (bf16 resident params, f32 master
    copy, error-feedback residuals in the gradient dtype) and training
    makes progress through the quantized wire."""
    params = {"w": jnp.ones((64, 32), jnp.bfloat16) * 0.5}
    opt = hvd.DistributedOptimizer(hvd.master_weights(optax.adamw(1e-2)),
                                   compression=hvd.Compression.int8)
    state = opt.init(params)

    @hvd.shard(in_specs=(P(), P(), hvd.batch_spec(2)),
               out_specs=(P(), P(), P()))
    def step(params, state, x):
        def loss(p):
            return jnp.sum((x.astype(jnp.bfloat16) @ p["w"]).astype(
                jnp.float32) ** 2)

        l, g = jax.value_and_grad(loss)(params)
        u, state = opt.update(g, state, params)
        return optax.apply_updates(params, u), state, l

    x = jax.random.normal(jax.random.PRNGKey(0), (2 * hvd.num_chips(), 64))
    p2, s2, l1 = step(params, state, x)
    p3, s3, l2 = step(p2, s2, x)
    assert p2["w"].dtype == jnp.bfloat16
    assert s2.inner.master["w"].dtype == jnp.float32  # master inside EF state
    # EF residuals carry in the gradient dtype (bf16 here — the residual
    # itself is quantized one level further; documented trade).
    assert jax.tree.leaves(s2.error)[0].dtype == jnp.bfloat16
    assert float(l2) < float(l1)


def test_accumulate_composes_with_master_weights_and_int8_ef(hvd):
    """The 468M-row recipe (VERDICT r4 item 3) as one pinned composition:
    hvd.accumulate_gradients microbatching feeding
    DistributedOptimizer(master_weights(adamw), compression=int8).  The
    accumulated-microbatch step must (a) keep all three state layers
    (bf16 resident params, f32 master, EF residuals), (b) make progress,
    and (c) match the full-batch step's update to quantization-free
    equality — accumulation happens BEFORE the wire, so the int8
    quantizer sees identical averaged gradients either way."""
    params = {"w": jnp.ones((64, 32), jnp.bfloat16) * 0.5}
    opt = hvd.DistributedOptimizer(hvd.master_weights(optax.adamw(1e-2)),
                                   compression=hvd.Compression.int8)
    state = opt.init(params)

    def make_step(n_micro):
        @hvd.shard(in_specs=(P(), P(), hvd.batch_spec(2)),
                   out_specs=(P(), P(), P()))
        def step(params, state, x):
            def loss(p, xb):
                return jnp.mean((xb.astype(jnp.bfloat16) @ p["w"]).astype(
                    jnp.float32) ** 2)

            if n_micro > 1:
                l, g = hvd.accumulate_gradients(
                    lambda p, xb: jax.value_and_grad(loss)(p, xb),
                    params, x, n_micro)
            else:
                l, g = jax.value_and_grad(lambda p: loss(p, x))(params)
            u, state2 = opt.update(g, state, params)
            return optax.apply_updates(params, u), state2, l

        return step

    x = jax.random.normal(jax.random.PRNGKey(0), (4 * hvd.num_chips(), 64))
    p_full, s_full, l_full = make_step(1)(params, state, x)
    p_acc, s_acc, l_acc = make_step(2)(params, state, x)
    assert p_acc["w"].dtype == jnp.bfloat16
    assert s_acc.inner.master["w"].dtype == jnp.float32
    assert jax.tree.leaves(s_acc.error)[0].dtype == jnp.bfloat16
    # Mean-reduced loss ⇒ microbatch accumulation reproduces the
    # full-batch gradients up to bf16 tolerance: XLA lowers the (B, K)
    # and (B/2, K) bf16 matmuls with different internal precision, so
    # per-row products differ at bf16 epsilon (measured ~7e-4 relative on
    # the loss) — the agreement pinned here is bf16-level, not bitwise.
    np.testing.assert_allclose(float(l_acc), float(l_full), rtol=5e-3)
    np.testing.assert_allclose(
        np.asarray(p_acc["w"], np.float32),
        np.asarray(p_full["w"], np.float32), rtol=1e-2, atol=1e-3)
    np.testing.assert_allclose(
        np.asarray(s_acc.inner.master["w"]),
        np.asarray(s_full.inner.master["w"]), rtol=1e-2, atol=1e-3)
    # And training continues to make progress from the accumulated state.
    _, _, l_next = make_step(2)(p_acc, s_acc, x)
    assert float(l_next) < float(l_acc)
