"""DistributedOptimizer + broadcast tests.

Mirrors the reference's optimizer/broadcast test matrix: gradient averaging
equals local math (reference test_torch.py:175-223 fused/async),
broadcast_parameters restores divergent state (test_torch.py:734-866),
broadcast_object round-trips scalars (torch/__init__.py:197-247 semantics).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P


def test_distributed_optimizer_averages_grads(hvd):
    n = hvd.num_chips()
    opt = hvd.DistributedOptimizer(optax.sgd(0.1))
    params = {"w": jnp.ones((8, 4)), "b": jnp.zeros((4,))}

    @hvd.shard(in_specs=(P(), hvd.batch_spec(2)), out_specs=P())
    def step(params, x):
        def loss(p):
            return jnp.sum((x @ p["w"] + p["b"]) ** 2) / x.shape[0]
        grads = jax.grad(loss)(params)
        state = opt.init(params)
        updates, _ = opt.update(grads, state, params)
        return optax.apply_updates(params, updates)

    x = jax.random.normal(jax.random.PRNGKey(0), (n * 2, 8))

    # Single-worker math on the full batch must equal the distributed result,
    # because averaging shard-mean gradients == full-batch mean gradient.
    def loss_full(p):
        return jnp.sum((x @ p["w"] + p["b"]) ** 2) / (x.shape[0] / n)
    g = jax.grad(lambda p: loss_full(p) / n)(params)
    ref = optax.apply_updates(params, optax.sgd(0.1).update(g, optax.sgd(0.1).init(params), params)[0])

    out = step(params, x)
    np.testing.assert_allclose(out["w"], ref["w"], rtol=1e-5)
    np.testing.assert_allclose(out["b"], ref["b"], rtol=1e-5)


def test_distributed_optimizer_eager_single_process(hvd):
    # Eager path: size()==1 in tests, so update must equal the wrapped one.
    opt = hvd.DistributedOptimizer(optax.adam(1e-3))
    params = {"w": jnp.ones((4,))}
    grads = {"w": jnp.full((4,), 2.0)}
    state = opt.init(params)
    updates, _ = opt.update(grads, state, params)
    ref_opt = optax.adam(1e-3)
    ref_updates, _ = ref_opt.update(grads, ref_opt.init(params), params)
    np.testing.assert_allclose(updates["w"], ref_updates["w"], rtol=1e-6)


def test_broadcast_parameters_in_mesh(hvd):
    @hvd.shard(in_specs=hvd.batch_spec(1), out_specs=P())
    def sync(x):
        # Each worker holds a different param shard value; root 2's value wins.
        return hvd.broadcast(x[0], root_rank=2)

    vals = jnp.arange(hvd.num_chips(), dtype=jnp.float32)
    out = sync(vals)
    assert float(out) == 2.0


def test_broadcast_parameters_pytree(hvd):
    tree = {"a": jnp.ones((3,)), "b": {"c": jnp.zeros((2, 2))}}
    out = hvd.broadcast_parameters(tree, root_rank=0)
    assert jax.tree.structure(out) == jax.tree.structure(tree)
    np.testing.assert_array_equal(out["a"], tree["a"])


def test_broadcast_optimizer_state(hvd):
    opt = optax.sgd(0.1, momentum=0.9)
    state = opt.init({"w": jnp.ones((4,))})
    out = hvd.broadcast_optimizer_state(state, root_rank=0)
    assert jax.tree.structure(jax.tree.map(np.asarray, out)) == \
        jax.tree.structure(jax.tree.map(np.asarray, state))


def test_broadcast_object(hvd):
    obj = {"epoch": 7, "name": "ckpt"}
    assert hvd.broadcast_object(obj, root_rank=0) == obj


def test_scale_learning_rate(hvd):
    assert hvd.scale_learning_rate(0.1) == pytest.approx(0.1 * hvd.num_chips())
