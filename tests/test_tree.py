"""Hierarchical coordinator tree (core/src/tree.cc, horovod_tpu/tree.py).

Four layers, cheapest first:

1. **Plan parity** — the Python topology mirror (tree.plan) against the
   native ``hvd_tree_plan`` over a knob grid: the launcher places relay
   sidecars from the Python answer and every rank activates from the
   native one, so a drift between them is a partitioned job.
2. **Agg-map grammar** — format/parse round-trip plus the malformed specs
   the launcher must reject before exporting them to a fleet.
3. **Fleet simulator** (core/src/fleet_sim.cc: REAL root/relay protocol
   code, scripted members) — steady-state convergence, the satellite-2
   pin that the root's aggregate fan-in is exactly ``num_groups`` frames
   per tick, and chaos drills: a SIGKILLed aggregator's standby promotes
   (EOF-driven) and a SIGSTOP partition recovers via the promote-silence
   path — survivors always converge, never hang.
4. **Real engine end to end** — ``python -m horovod_tpu.run`` at np=3
   with the tree forced on: the launcher spawns the relay sidecars and
   wires ``HVD_TPU_TREE_AGG_MAP``; allreduce values stay correct and
   ``control_plane_stats()`` reports tree_root/tree_member roles.
"""

import ctypes
import json
import os
import subprocess
import sys
import textwrap

import pytest

from _timing import scaled

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CORE = os.path.join(REPO, "horovod_tpu", "core")


# ---------------------------------------------------------------------------
# 1. Plan parity: tree.py mirror vs native hvd_tree_plan
# ---------------------------------------------------------------------------


def _native_plan(size, fanout, threshold, enable):
    from horovod_tpu.core import engine as engine_mod

    out = (ctypes.c_int * 4)()
    engine_mod.lib().hvd_tree_plan(size, fanout, threshold,
                                   1 if enable else 0, out)
    return {"active": bool(out[0]), "fanout": out[1],
            "num_groups": out[2], "depth": out[3]}


def test_plan_parity_against_native():
    from horovod_tpu import tree

    for size in (1, 2, 3, 4, 5, 16, 63, 64, 65, 129, 257, 513, 4096):
        for fanout in (0, 1, 2, 3, 8, 64, 128):
            for threshold in (0, 3, 256, 10000):
                for enable in (False, True):
                    py = tree.plan(size, fanout, threshold, enable)
                    nat = _native_plan(size, fanout, threshold, enable)
                    knobs = (size, fanout, threshold, enable)
                    assert py.active == nat["active"], (knobs, py, nat)
                    if py.active:
                        assert py.fanout == nat["fanout"], (knobs, py, nat)
                        assert py.num_groups == nat["num_groups"], (
                            knobs, py, nat)
                        assert py.depth == nat["depth"] == 2, (knobs, py, nat)


def test_plan_star_below_threshold():
    from horovod_tpu import tree

    # The threshold gate: same knobs, one rank short -> star.
    assert not tree.plan(255, 64, 256, True).active
    assert tree.plan(256, 64, 256, True).active
    # Enable is an opt-in regardless of size.
    assert not tree.plan(4096, 64, 256, False).active


def test_group_membership_partition():
    from horovod_tpu import tree

    for size, fanout in ((16, 4), (17, 4), (64, 8), (4096, 128)):
        p = tree.plan(size, fanout, 3, True)
        assert p.active
        seen = []
        for g in range(p.num_groups):
            members = tree.members_of(g, p)
            assert members, (size, fanout, g)
            assert all(tree.group_of(r, p) == g for r in members)
            seen.extend(members)
        # Workers 1..size-1 are covered exactly once; rank 0 is the root.
        assert seen == list(range(1, size))
        assert tree.group_of(0, p) == -1


# ---------------------------------------------------------------------------
# 2. Agg-map grammar
# ---------------------------------------------------------------------------


def test_agg_map_roundtrip():
    from horovod_tpu import tree

    eps = [(("127.0.0.1", 9001), ("127.0.0.1", 9002)),
           (("10.0.0.7", 9003), None)]
    spec = tree.format_agg_map(eps)
    assert spec == "0=127.0.0.1:9001|127.0.0.1:9002,1=10.0.0.7:9003"
    assert tree.parse_agg_map(spec, 2) == eps


@pytest.mark.parametrize("spec,groups", [
    ("", 1),                          # empty
    ("0=127.0.0.1:9001", 2),          # group 1 missing
    ("0=127.0.0.1", 1),               # no port
    ("0=127.0.0.1:0", 1),             # port 0
    ("0=127.0.0.1:9001|", 1),         # dangling standby separator
    ("1=127.0.0.1:9001", 1),          # group out of range
    ("x=127.0.0.1:9001", 1),          # non-numeric group
    ("127.0.0.1:9001", 1),            # no group key
])
def test_agg_map_malformed(spec, groups):
    from horovod_tpu import tree

    assert tree.parse_agg_map(spec, groups) is None


# ---------------------------------------------------------------------------
# 3. Fleet simulator: convergence, fan-in pin, chaos
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def fleet_sim():
    res = subprocess.run(["make", "-C", CORE, "fleet_sim"],
                         capture_output=True, text=True)
    assert res.returncode == 0, res.stderr[-3000:]
    return os.path.join(CORE, "fleet_sim")


def _run_sim(binary, *args):
    res = subprocess.run([binary, *args], capture_output=True, text=True,
                         timeout=scaled(300))
    lines = [ln for ln in res.stdout.splitlines()
             if "modeled_tick_us" in ln]
    assert res.returncode == 0 and lines, (
        res.returncode, res.stdout[-2000:], res.stderr[-2000:])
    return json.loads(lines[-1])


def test_fleet_sim_tree_converges(fleet_sim):
    r = _run_sim(fleet_sim, "--p", "16", "--fanout", "4", "--ticks", "8")
    assert r["ok"] and r["topology"] == "tree"
    assert r["num_groups"] == 4 and r["depth"] == 2
    assert r["modeled_tick_us"] > 0
    # Satellite pin: the root's aggregate fan-in is EXACTLY one frame per
    # group per tick — O(fanout), not O(size).  A star would see 15.
    assert r["agg_frames_per_tick"] == pytest.approx(4.0)


def test_fleet_sim_star_converges(fleet_sim):
    r = _run_sim(fleet_sim, "--p", "8", "--topology", "star",
                 "--ticks", "6")
    assert r["ok"] and r["topology"] == "star"
    assert r["depth"] == 1 and r["modeled_tick_us"] > 0


def test_fleet_sim_aggregator_sigkill_promotes_standby(fleet_sim):
    r = _run_sim(fleet_sim, "--p", "16", "--fanout", "4", "--ticks", "10",
                 "--chaos", "kill")
    assert r["ok"], r
    # EOF-driven promotion: the kill must be detected and recovered (the
    # measured figure is sub-2ms; the bound is lenient for loaded CI).
    assert 0 < r["mttr_ms"] < scaled(5000), r
    # The group's members re-attached to the promoted standby.
    assert r["reattaches"] >= 1, r


def test_fleet_sim_aggregator_sigstop_partition_recovers(fleet_sim):
    r = _run_sim(fleet_sim, "--p", "16", "--fanout", "4", "--ticks", "10",
                 "--chaos", "stop")
    assert r["ok"], r
    # No EOF arrives from a SIGSTOPed relay: recovery is the promote-
    # silence path (HVD_TPU_TREE_PROMOTE_SILENCE_MS, default 1000) plus
    # the members' own silence sweep, so the floor is ~1s.
    assert 0 < r["mttr_ms"] < scaled(20000), r
    assert r["reattaches"] >= 1, r


# ---------------------------------------------------------------------------
# 4. Real engine end to end through the launcher
# ---------------------------------------------------------------------------


_TREE_WORKER = textwrap.dedent("""
    import os
    os.environ.setdefault("XLA_FLAGS",
                          "--xla_force_host_platform_device_count=1")
    import numpy as np
    import horovod_tpu as hvd

    rank = int(os.environ["JAX_PROCESS_ID"])
    n = int(os.environ["JAX_NUM_PROCESSES"])
    assert os.environ.get("HVD_TPU_TREE_AGG_MAP"), \\
        "launcher did not wire the relay sidecars"
    hvd.init(coordinator_address=os.environ["JAX_COORDINATOR_ADDRESS"],
             num_processes=n, process_id=rank)
    S = float(n * (n + 1) // 2)
    for i in range(5):
        h = hvd.allreduce_async(np.full(8, float(rank + 1), np.float32),
                                average=False, name=f"tree.ar{i}")
        np.testing.assert_allclose(hvd.synchronize(h), np.full(8, S))
    st = hvd.control_plane_stats()
    expect = "tree_root" if rank == 0 else "tree_member"
    assert st["role"] == expect, st
    assert st["depth"] == 2 and st["fanout"] == 2, st
    if rank == 0:
        assert st["ticks"] > 0, st
        # One aggregator group: ~1 AGG frame per tick at the root (plus
        # occasional heartbeats), never the star's n-1.
        assert st["frames_per_tick"] < 1.5, st
    print(f"RANK{rank} OK", flush=True)
""")


def test_tree_engine_end_to_end_via_launcher():
    env = {**os.environ, "PYTHONPATH": REPO,
           "HVD_TPU_TREE_ENABLE": "1",
           "HVD_TPU_TREE_FANOUT": "2",
           "HVD_TPU_TREE_THRESHOLD": "3"}
    res = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.run", "-np", "3", "--",
         sys.executable, "-c", _TREE_WORKER],
        cwd=REPO, capture_output=True, text=True, timeout=scaled(300),
        env=env)
    assert res.returncode == 0, (res.stdout[-3000:], res.stderr[-2000:])
    for r in range(3):
        assert f"RANK{r} OK" in res.stdout, res.stdout[-3000:]


def test_control_plane_stats_loopback_and_unstarted():
    import numpy as np

    from horovod_tpu.core import engine as engine_mod
    from horovod_tpu.core.engine import OP_ALLREDUCE, NativeEngine
    from horovod_tpu.core.executors import local_executor

    # Module-level accessor with no started engine: the "none" row.
    # (Guarded: another in-process test may have init'd the singleton.)
    if engine_mod._engine is None:
        st = engine_mod.control_plane_stats()
        assert st["role"] == "none" and st["ticks"] == 0

    eng = NativeEngine(0, 1, executor=local_executor)
    try:
        h = eng.enqueue("cp.loop", np.ones(4, np.float32), OP_ALLREDUCE)
        eng.synchronize(h, timeout_s=scaled(60))
        st = eng.control_plane_stats()
        assert st["role"] == "loopback", st
        assert st["fanout"] == 0 and st["frames_rx"] == 0, st
    finally:
        eng.shutdown()
