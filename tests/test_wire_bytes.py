"""Wire-byte accounting for the eager data plane (VERDICT r2 item 1).

The reference's eager allreduce inherits MPI's ring economics: ~2n wire
bytes per rank regardless of job size (reference operations.cc:1242-1268).
Round 2's allgather+host-sum moved (P-1)*n per rank instead.  This
microbench measures REAL loopback traffic (/proc/net/dev) for a 4-process
job in both modes and asserts the device reduce-scatter route
(core/device_reduce.py) cuts wire bytes by ~P/2 = 2x, for the dense f32
wire and the int8 wire alike.

Accounting model (total rx across all ranks, K iterations of n bytes):
  gather:  P*(P-1)*n*K      device:  2*(P-1)*n*K      ratio: P/2
"""

import os
import subprocess

import pytest

from _timing import scaled
from test_multiprocess import PRELUDE, _run_workers_once

NPROCS = 4
ELEMS = 1 << 21          # 2 Mi f32 elements = 8 MiB dense, 2 MiB int8 wire
ITERS = 4

WIRE_WORKER = PRELUDE + """
import numpy as np
mode = os.environ["WB_MODE"]
N = int(os.environ["WB_ELEMS"])
K = int(os.environ["WB_ITERS"])
x = (np.random.RandomState(rank).rand(N).astype(np.float32) - 0.5)
if mode == "dense":
    for k in range(K):
        h = hvd.allreduce_async(x, average=False, name=f"wb.{k}")
        hvd.synchronize(h)
elif mode == "int8":
    for k in range(K):
        h = hvd.allreduce_async(x, average=False, name=f"wbq.{k}",
                                compression=hvd.Compression.int8)
        hvd.synchronize(h)
elif mode == "idle":
    pass
else:
    raise AssertionError(mode)
# Rendezvous before exit: a rank that exits early tears down the control
# plane and aborts peers still inside their last synchronize.
hvd.barrier(name="wb.done")
print(f"RANK{rank} OK", flush=True)
"""


def _lo_rx_bytes() -> int:
    with open("/proc/net/dev") as f:
        for line in f:
            line = line.strip()
            if line.startswith("lo:"):
                return int(line.split(":")[1].split()[0])
    raise AssertionError("no loopback interface in /proc/net/dev")


def _job_bytes(mode: str, algo: str | None = None,
               worker: str = WIRE_WORKER) -> int:
    """Loopback rx bytes for one 4-process job.  Retries infra noise with a
    FRESH counter read — a silent whole-job retry under one measurement
    would double-count traffic and corrupt the ratio assertions."""
    env = {"WB_MODE": mode, "WB_ELEMS": str(ELEMS), "WB_ITERS": str(ITERS)}
    if algo is not None:
        env["HVD_TPU_EAGER_REDUCE"] = algo
    last_err = ""
    for _attempt in range(2):
        before = _lo_rx_bytes()
        try:
            outs = _run_workers_once(worker, NPROCS, scaled(300), env)
        except subprocess.TimeoutExpired:
            last_err = "job timeout"
            continue
        if all(f"RANK{r} OK" in out for r, (out, _, _) in enumerate(outs)):
            return _lo_rx_bytes() - before
        last_err = "\n".join(err[-2000:] for _, err, _ in outs)
    raise AssertionError(f"wire-byte job {mode}/{algo} failed twice:\n"
                         f"{last_err}")


@pytest.mark.skipif(not os.path.exists("/proc/net/dev"),
                    reason="needs /proc/net/dev")
def test_device_reduce_halves_wire_bytes():
    # Boot/rendezvous overhead measured once and subtracted from each job.
    overhead = _job_bytes("idle", "device")
    payload = ELEMS * 4 * ITERS
    results = {}
    for mode in ("dense", "int8"):
        for algo in ("gather", "device"):
            raw = _job_bytes(mode, algo)
            results[(mode, algo)] = max(raw - overhead, 1)
    n_dense, n_int8 = payload, payload // 4
    expect = {
        ("dense", "gather"): NPROCS * (NPROCS - 1) * n_dense,
        ("dense", "device"): 2 * (NPROCS - 1) * n_dense,
        ("int8", "gather"): NPROCS * (NPROCS - 1) * n_int8,
        ("int8", "device"): 2 * (NPROCS - 1) * n_int8,
    }
    for key, got in results.items():
        print(f"{key}: measured {got/1e6:.1f} MB, model {expect[key]/1e6:.1f}"
              f" MB ({got/expect[key]:.2f}x of model)")

    dense_ratio = results[("dense", "gather")] / results[("dense", "device")]
    int8_ratio = results[("int8", "gather")] / results[("int8", "device")]
    # Model says P/2 = 2.0; margin for gloo framing + control plane noise.
    assert dense_ratio >= 1.7, f"dense wire reduction only {dense_ratio:.2f}x"
    assert int8_ratio >= 1.7, f"int8 wire reduction only {int8_ratio:.2f}x"
    # int8 wire is ~4x leaner than the dense wire on the same route.
    comp_ratio = results[("dense", "device")] / results[("int8", "device")]
    assert comp_ratio >= 2.5, f"int8 compression only {comp_ratio:.2f}x"


OPT_WORKER = PRELUDE + """
import jax.numpy as jnp
import numpy as np
import optax
N = int(os.environ["WB_ELEMS"])
K = int(os.environ["WB_ITERS"])
# A full DistributedOptimizer training step on the eager path: many
# leaves of mixed sizes totalling N f32 elements, so the wire carries
# the production (bucketed) gradient payload, not one raw collective.
sizes = [N // 2, N // 4, N // 8, N - (N // 2 + N // 4 + N // 8)]
rng = np.random.RandomState(rank)
params = {f"p{i}": jnp.asarray(rng.rand(s).astype(np.float32))
          for i, s in enumerate(sizes)}
opt = hvd.DistributedOptimizer(optax.sgd(0.01))
state = opt.init(params)
for k in range(K):
    grads = {f"p{i}": jnp.asarray(rng.rand(s).astype(np.float32) - 0.5)
             for i, s in enumerate(sizes)}
    updates, state = opt.update(grads, state, params)
    params = optax.apply_updates(params, updates)
hvd.barrier(name="wbopt.done")
print(f"RANK{rank} OK", flush=True)
"""


@pytest.mark.skipif(not os.path.exists("/proc/net/dev"),
                    reason="needs /proc/net/dev")
def test_distributed_optimizer_step_matches_ring_model():
    """The scaling projection's wire model, asserted for the FULL
    DistributedOptimizer step (not just raw collectives): K eager steps
    over V bytes of gradients at P ranks must move ≈ 2·(P−1)·V·K total
    loopback bytes (ring reduce-scatter → allgather), within framing
    margins.  VERDICT r3 weak-item 5."""
    overhead = _job_bytes("idle")
    measured = _job_bytes("opt", worker=OPT_WORKER) - overhead
    model = 2 * (NPROCS - 1) * ELEMS * 4 * ITERS
    ratio = measured / model
    print(f"optimizer step: measured {measured/1e6:.1f} MB, ring model "
          f"{model/1e6:.1f} MB ({ratio:.2f}x)")
    # Ring-optimal within framing/control noise; far below the P-1=3x of
    # a naive gather transport.
    assert 0.8 <= ratio <= 1.6, f"optimizer wire {ratio:.2f}x of ring model"
