"""ZeRO-1 optimizer sharding: trains identically to replicated-state DP
while holding only 1/K of the optimizer state per device (beyond reference
scope — SURVEY §2.9 notes upstream replicates optimizer state)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

from horovod_tpu.parallel import zero_optimizer


def _params():
    k = jax.random.PRNGKey(0)
    return {"w": jax.random.normal(k, (5, 3)),
            "b": jnp.zeros((3,)),
            "v": jax.random.normal(jax.random.fold_in(k, 1), (7,))}


def _grads(params, x):
    def loss(p):
        return jnp.sum((x @ p["w"] + p["b"]) ** 2) + jnp.sum(p["v"] ** 2)

    return jax.grad(loss)(params)


def test_zero_matches_replicated_adam(hvd):
    """N steps of zero_optimizer(adam) == N steps of plain adam on the
    full (averaged) gradients."""
    n = hvd.size() if hvd.size() > 1 else 8
    params = _params()
    ztx = zero_optimizer(optax.adam(1e-2))

    def steps(params, xs):
        state = ztx.init(params)

        def body(carry, x):
            params, state = carry
            updates, state = ztx.update(_grads(params, x), state, params)
            return (optax.apply_updates(params, updates), state), None

        (params, _), _ = jax.lax.scan(body, (params, state), xs)
        return params

    xs = jax.random.normal(jax.random.PRNGKey(3), (4, n, 2, 5))
    sharded = jax.jit(hvd.shard(
        steps, in_specs=(P(), P(None, "hvd")), out_specs=P()))
    out = sharded(params, xs)

    # Reference: plain adam on the mean-over-devices gradient each step.
    tx = optax.adam(1e-2)
    p_ref = params
    st = tx.init(p_ref)
    for t in range(4):
        gs = [_grads(p_ref, xs[t, d]) for d in range(n)]
        g = jax.tree.map(lambda *a: sum(a) / n, *gs)
        u, st = tx.update(g, st, p_ref)
        p_ref = optax.apply_updates(p_ref, u)

    for k in params:
        np.testing.assert_allclose(np.asarray(out[k]),
                                   np.asarray(p_ref[k]),
                                   atol=1e-5, rtol=1e-5)


def test_zero_state_is_sharded(hvd):
    """Per-device optimizer state must hold ~1/K of the flattened params."""
    n = 8
    params = _params()
    total = sum(p.size for p in jax.tree.leaves(params))  # 5*3+3+7 = 25
    ztx = zero_optimizer(optax.adam(1e-2))

    def init(params):
        # adam state: (ScaleByAdamState(count, mu, nu), EmptyState); mu is
        # the flat per-device shard (count is 0-d and can't be stacked).
        return ztx.init(params)[0].mu

    mu = np.asarray(jax.jit(
        hvd.shard(init, in_specs=P(), out_specs=P("hvd")))(params))
    chunk = -(-total // n)  # ceil -> padded chunk per device
    assert mu.size == n * chunk, (mu.size, n, chunk)


def test_zero_momentum_semantics(hvd):
    """SGD+momentum through zero matches full-state SGD+momentum."""
    n = 8
    params = {"w": jnp.arange(10.0)}
    ztx = zero_optimizer(optax.sgd(0.1, momentum=0.9))

    def two_steps(params):
        state = ztx.init(params)
        for _ in range(2):
            grads = {"w": params["w"] * 0.5}
            updates, state = ztx.update(grads, state, params)
            params = optax.apply_updates(params, updates)
        return params

    out = jax.jit(hvd.shard(two_steps, in_specs=P(), out_specs=P()))(params)

    tx = optax.sgd(0.1, momentum=0.9)
    p = params
    st = tx.init(p)
    for _ in range(2):
        u, st = tx.update({"w": p["w"] * 0.5}, st, p)
        p = optax.apply_updates(p, u)
    np.testing.assert_allclose(np.asarray(out["w"]), np.asarray(p["w"]),
                               rtol=1e-6)


def test_zero_mixed_dtypes_round_trip(hvd):
    """Mixed bf16/f32 trees must come back in their own dtypes (the wire
    promotes, _unflatten casts back)."""
    params = {"w": jnp.ones((6,), jnp.bfloat16), "b": jnp.ones((4,))}
    ztx = zero_optimizer(optax.sgd(0.1))

    def step(params):
        grads = jax.tree.map(jnp.ones_like, params)
        state = ztx.init(params)
        updates, _ = ztx.update(grads, state, params)
        return updates

    updates = jax.jit(hvd.shard(step, in_specs=P(), out_specs=P()))(params)
    assert updates["w"].dtype == jnp.bfloat16
    assert updates["b"].dtype == jnp.float32


def test_distributed_optimizer_sharded_state_flag(hvd):
    """hvd.DistributedOptimizer(sharded_state=True) is the ZeRO-1 wrapper."""
    import horovod_tpu as h

    tx = h.DistributedOptimizer(optax.sgd(0.1), sharded_state=True)
    params = {"w": jnp.arange(8.0)}

    def step(params):
        state = tx.init(params)
        updates, _ = tx.update({"w": jnp.ones(8)}, state, params)
        return optax.apply_updates(params, updates)

    out = jax.jit(hvd.shard(step, in_specs=P(), out_specs=P()))(params)
    # every device contributes grad=1; reduce-scatter sums to 8, averaging
    # restores 1 -> sgd step of -0.1
    np.testing.assert_allclose(np.asarray(out["w"]),
                               np.arange(8.0) - 0.1, rtol=1e-6)


def test_zero_hierarchical_axes(hvd):
    """ZeRO over a 2-D (dcn, ici) data mesh: shard index linearizes across
    both axes; training still matches replicated adam."""
    from jax.sharding import Mesh

    devs = np.array(jax.devices()[:8]).reshape(2, 4)
    mesh = Mesh(devs, ("dcn", "ici"))
    params = {"w": jnp.arange(12.0), "b": jnp.ones((5,))}
    ztx = zero_optimizer(optax.adam(1e-2), axis_name=("dcn", "ici"))

    def steps(params):
        state = ztx.init(params)
        for _ in range(2):
            grads = jax.tree.map(lambda p: p * 0.1, params)
            updates, state = ztx.update(grads, state, params)
            params = optax.apply_updates(params, updates)
        return params

    out = jax.jit(jax.shard_map(steps, mesh=mesh, in_specs=P(),
                                out_specs=P(), check_vma=False))(params)

    tx = optax.adam(1e-2)
    p = params
    st = tx.init(p)
    for _ in range(2):
        u, st = tx.update(jax.tree.map(lambda q: q * 0.1, p), st, p)
        p = optax.apply_updates(p, u)
    for k in params:
        np.testing.assert_allclose(np.asarray(out[k]), np.asarray(p[k]),
                                   atol=1e-6)
