"""Zigzag ring attention: load-balanced causal sequence parallelism.

No reference analog (the reference has no attention, SURVEY §2.9); the test
contract follows the suite's rule: sharded attention must reproduce dense
single-device attention, including gradients, with the zigzag layout's
permutation round-tripping exactly.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from horovod_tpu.models.transformer import dense_causal_attention
from horovod_tpu.parallel import (
    zigzag_inverse_permutation,
    zigzag_permutation,
    zigzag_positions,
    zigzag_ring_flash_attention,
)

N = 8  # virtual chips (conftest)


def _qkv(b=2, s=32, h=4, d=8, dtype=jnp.float32):
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    return tuple(jax.random.normal(k, (b, s, h, d), dtype) for k in ks)


def _sharded_zigzag(causal, s, block=2):
    mesh = Mesh(np.array(jax.devices()), ("sp",))
    perm = zigzag_permutation(s, N)
    inv = zigzag_inverse_permutation(s, N)

    def run(q, k, v):
        out = jax.shard_map(
            lambda q, k, v: zigzag_ring_flash_attention(
                q, k, v, "sp", causal, block, block),
            mesh=mesh, in_specs=P(None, "sp"), out_specs=P(None, "sp"),
            check_vma=False)(q[:, perm], k[:, perm], v[:, perm])
        return out[:, inv]

    return run


def test_permutation_round_trips():
    perm = zigzag_permutation(32, N)
    inv = zigzag_inverse_permutation(32, N)
    np.testing.assert_array_equal(perm[inv], np.arange(32))
    # rank r's shard = chunks (r, 2n-1-r): first shard is [c0 | c15]
    c = 32 // (2 * N)
    np.testing.assert_array_equal(perm[: 2 * c], [0, 1, 30, 31])


def test_positions_match_permutation(hvd):
    mesh = Mesh(np.array(jax.devices()), ("sp",))
    pos = jax.shard_map(lambda: zigzag_positions(4, "sp"), mesh=mesh,
                        in_specs=(), out_specs=P("sp"), check_vma=False)()
    np.testing.assert_array_equal(np.asarray(pos), zigzag_permutation(32, N))


@pytest.mark.parametrize("causal", [True, False])
def test_zigzag_matches_dense(hvd, causal):
    q, k, v = _qkv()
    out = _sharded_zigzag(causal, 32)(q, k, v)
    ref = dense_causal_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_zigzag_grads_match_dense(hvd):
    q, k, v = _qkv(s=16)
    run = _sharded_zigzag(True, 16)

    def loss_zz(q, k, v):
        return (run(q, k, v).astype(jnp.float32) ** 2).sum()

    def loss_dense(q, k, v):
        return (dense_causal_attention(q, k, v, causal=True)
                .astype(jnp.float32) ** 2).sum()

    g1 = jax.grad(loss_zz, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(a, b, atol=5e-4, rtol=5e-4)


def test_zigzag_rejects_indivisible(hvd):
    with pytest.raises(ValueError, match="divisible"):
        zigzag_permutation(12, N)


def test_transformer_with_zigzag_attention(hvd):
    """LM logits through zigzag layout == dense transformer, token-exact."""
    from horovod_tpu.models import Transformer, TransformerConfig
    from horovod_tpu.parallel import make_zigzag_ring_flash_attention

    cfg = dict(vocab_size=64, num_layers=2, num_heads=2, head_dim=8,
               embed_dim=16, mlp_dim=32, dtype=jnp.float32)
    s = 32
    tokens = jax.random.randint(jax.random.PRNGKey(3), (2, s), 0, 64)
    dense_model = Transformer(TransformerConfig(**cfg))
    params = dense_model.init(jax.random.PRNGKey(0), tokens)
    ref = dense_model.apply(params, tokens)

    zz_model = Transformer(TransformerConfig(
        **cfg, attention_fn=make_zigzag_ring_flash_attention(  # hvd-lint: disable=HVD108
            "sp", block_q=2, block_k=2)))
    mesh = Mesh(np.array(jax.devices()), ("sp",))
    perm = zigzag_permutation(s, N)
    inv = zigzag_inverse_permutation(s, N)
    s_local = s // N

    def fwd(params, toks):
        return zz_model.apply(params, toks,
                              positions=zigzag_positions(s_local, "sp"))

    out = jax.shard_map(
        fwd, mesh=mesh, in_specs=(P(), P(None, "sp")),
        out_specs=P(None, "sp"), check_vma=False)(params, tokens[:, perm])
    np.testing.assert_allclose(out[:, inv], ref, atol=2e-4, rtol=2e-4)
